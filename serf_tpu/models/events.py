"""Device->host event streaming: per-round deltas without killing throughput.

The device analog of the host event pipeline (SURVEY.md §7 stage 4 and §5
"host/device event streaming"): rather than shipping every node's state each
round, reduce on device to compact summaries — newly-learned counts per
fact, first-full-coverage rounds, per-fact knower counts — and only ship
those.  A host-side ``DeviceEventStream`` diffs consecutive summaries into
MemberEvent/UserEvent-like records.
"""

from __future__ import annotations

from typing import List, NamedTuple

import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_DEAD,
    K_JOIN,
    K_LEAVE,
    K_SUSPECT,
    K_USER_EVENT,
    unpack_bits,
)


class RoundSummary(NamedTuple):
    """Per-round device-side reduction (small: O(K) + scalars)."""

    round: jnp.ndarray          # i32
    knowers: jnp.ndarray        # i32[K] alive nodes knowing each fact
    alive_count: jnp.ndarray    # i32
    fact_subject: jnp.ndarray   # i32[K]
    fact_kind: jnp.ndarray      # u8[K]
    fact_valid: jnp.ndarray     # bool[K]


def summarize(state: GossipState, cfg: GossipConfig) -> RoundSummary:
    known = unpack_bits(state.known, cfg.k_facts)
    alive = state.alive[:, None]
    return RoundSummary(
        round=state.round,
        knowers=jnp.sum(known & alive, axis=0).astype(jnp.int32),
        alive_count=jnp.sum(state.alive).astype(jnp.int32),
        fact_subject=state.facts.subject,
        fact_kind=state.facts.kind,
        fact_valid=state.facts.valid,
    )


class DeviceEvent(NamedTuple):
    """A host-consumable protocol event derived from summary diffs."""

    round: int
    kind: str          # "fact-born" | "fully-disseminated" | "retired"
    fact_kind: int     # K_* constant
    subject: int
    knowers: int


_KIND_NAMES = {K_JOIN: "join", K_LEAVE: "leave", K_SUSPECT: "suspect",
               K_DEAD: "dead", K_USER_EVENT: "user-event"}


class DeviceEventStream:
    """Diff consecutive RoundSummaries into discrete events (host side)."""

    def __init__(self, cfg: GossipConfig):
        self.cfg = cfg
        self._prev: RoundSummary | None = None
        self._full_seen: set = set()

    def push(self, summary: RoundSummary) -> List[DeviceEvent]:
        events: List[DeviceEvent] = []
        cur_valid = summary.fact_valid
        knowers = summary.knowers
        alive = int(summary.alive_count)
        rnd = int(summary.round)
        prev = self._prev
        for slot in range(self.cfg.k_facts):
            valid = bool(cur_valid[slot])
            subject = int(summary.fact_subject[slot])
            fkind = int(summary.fact_kind[slot])
            key = (slot, subject, fkind)
            was_valid = prev is not None and bool(prev.fact_valid[slot]) and \
                int(prev.fact_subject[slot]) == subject and \
                int(prev.fact_kind[slot]) == fkind
            if valid and not was_valid:
                events.append(DeviceEvent(rnd, "fact-born", fkind, subject,
                                          int(knowers[slot])))
                self._full_seen.discard(key)
            if valid and int(knowers[slot]) >= alive and key not in self._full_seen:
                self._full_seen.add(key)
                events.append(DeviceEvent(rnd, "fully-disseminated", fkind,
                                          subject, int(knowers[slot])))
        self._prev = summary
        return events


def kind_name(fact_kind: int) -> str:
    return _KIND_NAMES.get(fact_kind, f"kind-{fact_kind}")
