"""Device->host event streaming: per-round deltas without killing throughput.

The device analog of the host event pipeline (SURVEY.md §7 stage 4 and §5
"host/device event streaming"): rather than shipping every node's state each
round, reduce on device to compact summaries — newly-learned counts per
fact, first-full-coverage rounds, per-fact knower counts — and only ship
those.  A host-side ``DeviceEventStream`` diffs consecutive summaries into
MemberEvent/UserEvent-like records.
"""

from __future__ import annotations

from typing import List, NamedTuple

import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_DEAD,
    K_JOIN,
    K_LEAVE,
    K_SUSPECT,
    K_USER_EVENT,
    unpack_bits,
)


class RoundSummary(NamedTuple):
    """Per-round device-side reduction (small: O(K) + scalars)."""

    round: jnp.ndarray          # i32
    knowers: jnp.ndarray        # i32[K] alive nodes knowing each fact
    alive_count: jnp.ndarray    # i32
    fact_subject: jnp.ndarray   # i32[K]
    fact_kind: jnp.ndarray      # u8[K]
    fact_valid: jnp.ndarray     # bool[K]


def summarize(state: GossipState, cfg: GossipConfig) -> RoundSummary:
    known = unpack_bits(state.known, cfg.k_facts)
    alive = state.alive[:, None]
    return RoundSummary(
        round=state.round,
        knowers=jnp.sum(known & alive, axis=0).astype(jnp.int32),
        alive_count=jnp.sum(state.alive).astype(jnp.int32),
        fact_subject=state.facts.subject,
        fact_kind=state.facts.kind,
        fact_valid=state.facts.valid,
    )


class DeviceEvent(NamedTuple):
    """A host-consumable protocol event derived from summary diffs."""

    round: int
    kind: str          # "fact-born" | "fully-disseminated" | "retired"
    fact_kind: int     # K_* constant
    subject: int
    knowers: int


_KIND_NAMES = {K_JOIN: "join", K_LEAVE: "leave", K_SUSPECT: "suspect",
               K_DEAD: "dead", K_USER_EVENT: "user-event"}


class DeviceEventStream:
    """Diff consecutive RoundSummaries into discrete events (host side).

    ``push`` lands the summary as ONE device→host transfer
    (``jax.device_get`` of the whole pytree) and diffs with vectorized
    numpy — no per-slot device syncs, so the stream scales to the 1M-node
    streaming story (round-1 verdict, weak #8).
    """

    def __init__(self, cfg: GossipConfig):
        self.cfg = cfg
        self._prev = None              # host-side numpy RoundSummary
        self._full_seen: set = set()

    def push(self, summary: RoundSummary) -> List[DeviceEvent]:
        import numpy as np

        import jax

        host = RoundSummary(*(np.asarray(x) for x in jax.device_get(summary)))
        rnd = int(host.round)
        alive = int(host.alive_count)
        valid = host.fact_valid
        prev = self._prev

        if prev is None:
            same_identity = np.zeros_like(valid)
            prev_valid = np.zeros_like(valid)
        else:
            same_identity = ((prev.fact_subject == host.fact_subject)
                             & (prev.fact_kind == host.fact_kind)
                             & prev.fact_valid)
            prev_valid = prev.fact_valid

        born = valid & ~same_identity
        # a previously-valid fact whose slot was overwritten (identity
        # changed) or invalidated has retired from the ring
        retired = prev_valid & ~(valid & same_identity)
        full = valid & (host.knowers >= alive)

        events: List[DeviceEvent] = []
        for slot in np.nonzero(retired)[0]:
            key = (int(slot), int(prev.fact_subject[slot]),
                   int(prev.fact_kind[slot]))
            self._full_seen.discard(key)
            # the retired fact's last observed knower count — host.knowers
            # already describes the slot's NEW occupant
            events.append(DeviceEvent(rnd, "retired", key[2], key[1],
                                      int(prev.knowers[slot])))
        for slot in np.nonzero(born)[0]:
            key = (int(slot), int(host.fact_subject[slot]),
                   int(host.fact_kind[slot]))
            self._full_seen.discard(key)
            events.append(DeviceEvent(rnd, "fact-born", key[2], key[1],
                                      int(host.knowers[slot])))
        for slot in np.nonzero(full)[0]:
            key = (int(slot), int(host.fact_subject[slot]),
                   int(host.fact_kind[slot]))
            if key not in self._full_seen:
                self._full_seen.add(key)
                events.append(DeviceEvent(rnd, "fully-disseminated", key[2],
                                          key[1], int(host.knowers[slot])))
        self._prev = host
        return events


def kind_name(fact_kind: int) -> str:
    return _KIND_NAMES.get(fact_kind, f"kind-{fact_kind}")
