"""Device-plane checkpoint/resume: snapshot the whole simulated cluster.

The device analog of the host snapshotter (SURVEY.md §7 stage 9): the
``ClusterState``/``GossipState`` pytree is written as a flat ``.npz``
(atomic-rename on save) and restored bit-exactly — resume continues from the
same round with the same RNG discipline (keys are caller supplied, so a
resumed run with the same keys is identical to an unbroken one; pinned by
tests).  Restore fails closed (``ValueError``) on corrupt files and on any
shape or dtype mismatch against the template.

Sharded flagship states round-trip too: ``save`` GATHERS (``np.asarray``
on a node-sharded jax.Array pulls every addressable shard), so the
on-disk artifact is mesh-agnostic; ``restore(..., mesh=)`` RE-SHARDS the
loaded pytree onto the given mesh — after validating that the mesh size
divides every node-sharded axis, so a device-count mismatch fails closed
with a clear error instead of an XLA shape crash.

Every checkpoint is stamped with the pinned **pytree schema version**
(serflint's ``serf_tpu/analysis/pins/schema_pins.json``): a leaf-spec
change now fails restore with a *versioned* error pointing at
MIGRATION.md instead of the shape-mismatch surprise that recurred in
PR 3 and PR 5.  Checkpoints written before the stamp existed fall back
to the per-array shape/dtype validation below.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: reserved npz key for the schema stamp (never a pytree leaf: keystr
#: paths always start with a dot/bracket)
_SCHEMA_KEY = "__pytree_schema_version__"

_schema_version_cache: Optional[int] = None


def _schema_version() -> int:
    # deferred + cached: the runtime device plane must not import the
    # analysis package (or re-read its pins file) on every save/restore
    global _schema_version_cache
    if _schema_version_cache is None:
        from serf_tpu.analysis.schema import pytree_schema_version
        _schema_version_cache = pytree_schema_version()
    return _schema_version_cache


def _flatten(state) -> dict:
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    return {jax.tree_util.keystr(path): np.asarray(leaf) for path, leaf in flat}


def save(path: str, state: Any) -> None:
    """Write the state pytree; atomic replace so a crash never leaves a
    half-written checkpoint (same guarantee as the host snapshot
    compactor).  Sharded states gather here (``np.asarray`` pulls all
    addressable shards) — the artifact is mesh-agnostic."""
    arrays = _flatten(state)
    arrays[_SCHEMA_KEY] = np.asarray(_schema_version(), np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


def _validate_mesh(state: Any, mesh) -> Any:
    """Fail-closed re-shard: every axis the node sharding would split
    must be divisible by the mesh size (a 1M-node checkpoint restored
    onto a 7-device mesh must raise, not crash inside XLA)."""
    from serf_tpu.parallel.mesh import NODE_AXIS, state_shardings

    shardings = state_shardings(state, mesh)
    d = int(mesh.size)
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    flat_sh = jax.tree_util.tree_leaves(shardings)
    for (path_k, leaf), sh in zip(flat, flat_sh):
        for axis, name in enumerate(sh.spec):
            if name == NODE_AXIS and leaf.shape[axis] % d != 0:
                raise ValueError(
                    f"checkpoint re-shard device-count mismatch: array "
                    f"{jax.tree_util.keystr(path_k)!r} axis {axis} of "
                    f"size {leaf.shape[axis]} is not divisible by the "
                    f"{d}-device mesh — restore with a device count "
                    f"that divides the node axis")
    return jax.device_put(state, shardings)


def restore(path: str, template: Any, mesh=None) -> Any:
    """Load into the shape of ``template`` (the make_* result for the same
    config); raises FileNotFoundError/ValueError on missing or mismatched
    checkpoints.  ``mesh`` re-shards the restored pytree onto a device
    mesh (``parallel.mesh.state_shardings``), failing closed on a
    device-count mismatch."""
    import zipfile

    try:
        with np.load(path) as data:
            if _SCHEMA_KEY in data:
                found = int(data[_SCHEMA_KEY])
                current = _schema_version()
                if found != current:
                    raise ValueError(
                        f"checkpoint {path!r} was written at pytree "
                        f"schema version {found}, this build is at "
                        f"{current} — the GossipState/ClusterState leaf "
                        "spec changed since it was saved; see "
                        "MIGRATION.md ('Schema versioning') for the "
                        "bump workflow")
            flat, treedef = jax.tree_util.tree_flatten_with_path(template)
            leaves = []
            for path_k, leaf in flat:
                key = jax.tree_util.keystr(path_k)
                if key not in data:
                    # back-compat for checkpoints written before the
                    # round-5 fields.  The CACHE fields are lossless to
                    # default (sendable_round = -1 means "stale, never
                    # read" — the first cached selection recomputes).
                    # The TOMBSTONE default is lossy-but-recoverable:
                    # already-retired deaths are forgotten on resume and
                    # get re-suspected/re-declared by the detector —
                    # acceptable degradation, NOT lossless.
                    if key.endswith((".sendable", ".tombstone")):
                        leaves.append(jnp.zeros_like(leaf))
                        continue
                    if key.endswith(".sendable_round"):
                        leaves.append(jnp.asarray(-1, leaf.dtype))
                        continue
                    raise ValueError(f"checkpoint missing array {key!r}")
                arr = data[key]
                if arr.shape != leaf.shape:
                    raise ValueError(
                        f"checkpoint array {key!r} has shape {arr.shape}, "
                        f"state expects {leaf.shape}")
                if arr.dtype != np.asarray(leaf).dtype:
                    raise ValueError(
                        f"checkpoint array {key!r} has dtype {arr.dtype}, "
                        f"state expects {np.asarray(leaf).dtype}")
                leaves.append(jnp.asarray(arr, dtype=leaf.dtype))
            state = jax.tree_util.tree_unflatten(treedef, leaves)
            if mesh is not None:
                state = _validate_mesh(state, mesh)
            return state
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, KeyError, OSError) as e:
        # any zip/npy-level malformation fails closed as ValueError
        raise ValueError(f"corrupt checkpoint {path!r}: {e}") from e
