"""HBM traffic accounting: bytes/round for the device-plane kernels.

VERDICT r4 next-1a: make perf progress measurable without TPU access.
This module turns the prose "~500 MB/round" into tracked numbers two ways:

1. **Analytic per-plane model** (``round_traffic``): enumerates the bytes
   each phase of the flagship ``cluster_round`` moves through HBM, with
   cadence amortization (probe_every, push_pull_every, CLAMP_EVERY) and
   regime awareness (which skip-gates are open).  Every entry cites the
   code path it models; ``tests/test_accounting.py`` pins the totals and
   the dominators, so a kernel change that regresses traffic fails a test
   instead of hiding until the next TPU session.
2. **Compiled-HLO cross-check** (``hlo_bytes_per_round``): XLA's own
   ``cost_analysis()['bytes accessed']`` on the compiled executable.
   Fusion decisions differ per backend, so the test asserts the analytic
   model lands within a band of the compiled number rather than equality.

The regimes map to the protocol states the bench measures:

- ``"sustained"``: the headline workload — continuous event injection
  keeps the gossip gate open; detection gates (refute/declare) closed
  (a healthy loaded cluster).  Learns happen ~every round, so the merge
  stamp pass runs.
- ``"active"``: gossip gate open but nothing new learned (the
  fully-disseminated window before the gate closes) — the merge stamp
  pass is skipped (bit-exact identity, ``round_step``).
- ``"quiescent"``: gossip gate closed (``round - last_learn >=
  transmit_limit``): select/exchange/merge all skipped; only the probe
  sweep, the amortized clamp, and Vivaldi still run.
- ``"detection"``: the detection-hot window — on top of the sustained
  regime, the refute/declare skip-gates are OPEN (pending accusations /
  live suspicions), so their bodies' plane scans and bounded injections
  run.  This is the regime ``cluster_round_active_rps`` measures and
  why it runs several times slower than steady: declare's expiry scan
  re-reads the stamp plane.

Bandwidth arithmetic: a v5e chip streams ~819 GB/s from HBM, so the
single-chip round-rate ceiling is roughly ``819e9 / total_bytes``
(``ceiling_rounds_per_sec``) — the number the bench's measured rps should
be judged against (STATUS.md).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from serf_tpu.models.dissemination import (
    CLAMP_EVERY,
    STAMP_UNIT,
    GossipConfig,
)

#: v5e HBM bandwidth, bytes/s (the ceiling arithmetic in STATUS.md)
V5E_HBM_BYTES_PER_S = 819e9
#: v5e inter-chip interconnect, bytes/s per chip (public spec: 1600 Gbps
#: ICI per chip on v5e)
V5E_ICI_BYTES_PER_S = 200e9
#: per-collective launch latency (the α of the hierarchy-aware α-β comm
#: model, "A Model for Communication in Clusters of Multi-core Machines"
#: PAPERS.md): what one ppermute hop or one all-gather dispatch costs
#: before any byte moves.  ~1 µs is the right order for an on-chip ICI
#: launch; the schedule decision is insensitive to 2-3× error here
#: because the crossover block size scales linearly in it.
ICI_HOP_ALPHA_S = 1e-6


@dataclasses.dataclass(frozen=True)
class Entry:
    """One modeled HBM pass: ``bytes`` moved every ``1/cadence`` rounds."""

    phase: str       # which protocol phase (selection, merge, vivaldi, ...)
    plane: str       # which array (stamp, known, vivaldi, ...)
    rw: str          # "R", "W", or "RW"
    nbytes: float    # bytes touched per occurrence
    cadence: float   # occurrences per round (1.0, 1/probe_every, ...)
    where: str       # code path modeled (file:function)

    @property
    def amortized(self) -> float:
        return self.nbytes * self.cadence


@dataclasses.dataclass
class TrafficReport:
    n: int
    k: int
    regime: str
    entries: List[Entry]
    #: which dispatch path the entries model ("xla" | "kernels" | "fused")
    path: str = "xla"
    #: bytes of one full copy of each N-sized plane (for pass counting)
    plane_sizes: Optional[dict] = None

    @property
    def total_bytes(self) -> float:
        return sum(e.amortized for e in self.entries)

    def passes_by_plane(self) -> dict:
        """Amortized full-plane streaming passes per round, per plane:
        ``by_plane()[p] / plane_sizes[p]`` — the "how many times does
        this round re-stream the plane" number the fused-kernel work is
        judged on (an R+W counts as 2 passes).  Planes without a size
        (host-side or K-sized) are omitted."""
        if not self.plane_sizes:
            return {}
        return {p: b / self.plane_sizes[p]
                for p, b in self.by_plane().items()
                if self.plane_sizes.get(p)}

    def by_plane(self) -> dict:
        out: dict = {}
        for e in self.entries:
            out[e.plane] = out.get(e.plane, 0.0) + e.amortized
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def by_phase(self) -> dict:
        out: dict = {}
        for e in self.entries:
            out[e.phase] = out.get(e.phase, 0.0) + e.amortized
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def dominator(self) -> str:
        return next(iter(self.by_plane()))

    def ceiling_rounds_per_sec(self,
                               hbm=V5E_HBM_BYTES_PER_S) -> float:
        return hbm / max(self.total_bytes, 1.0)

    def table(self) -> str:
        lines = [f"HBM traffic model: n={self.n} k={self.k} "
                 f"regime={self.regime}",
                 f"{'phase':<12} {'plane':<10} {'rw':<3} "
                 f"{'MB/occur':>9} {'cad':>6} {'MB/round':>9}  where"]
        for e in sorted(self.entries, key=lambda e: -e.amortized):
            lines.append(
                f"{e.phase:<12} {e.plane:<10} {e.rw:<3} "
                f"{e.nbytes / 1e6:>9.2f} {e.cadence:>6.3f} "
                f"{e.amortized / 1e6:>9.2f}  {e.where}")
        by_plane = ", ".join(f"{p}={b / 1e6:.1f}MB"
                             for p, b in self.by_plane().items())
        lines.append(f"TOTAL {self.total_bytes / 1e6:.1f} MB/round "
                     f"({by_plane})")
        lines.append(f"v5e single-chip ceiling ~"
                     f"{self.ceiling_rounds_per_sec():,.0f} rounds/s")
        return "\n".join(lines)


#: the kernel dispatch paths the byte model prices (round_traffic.path):
#: "xla" = the plain-XLA phases (the model of record, fusion ASSUMED);
#: "kernels" = the PR-3 standalone pallas kernels (cache-invalidating —
#: every selection re-reads the stamp plane); "fused" = the fused-round
#: family (cache maintained IN the merge kernel; the selection's stamp
#: pass is gone and every per-phase pass is one authored DMA stream, so
#: the "xla" path's fusion assumptions become construction guarantees).
KERNEL_PATHS = ("xla", "kernels", "fused")


def round_traffic(cfg, regime: str = "sustained",
                  sustained_rate: int = 2,
                  path: str = "xla",
                  stamp_deferred: Optional[bool] = None) -> TrafficReport:
    """Analytic HBM model of one flagship ``cluster_round`` (swim.py).

    ``cfg`` is a ``ClusterConfig``; pass ``regime`` per the module
    docstring and ``path`` per :data:`KERNEL_PATHS`.  Returns a
    :class:`TrafficReport` whose entries each cite the code they model.
    The ``"xla"`` path assumes XLA fuses elementwise chains
    (unpack/compare/select feed their consumer without materializing) —
    the HLO cross-check in tests keeps that assumption honest; the
    pallas paths' entries are authored DMA streams, exact by
    construction.

    ``stamp_deferred`` models the quarter-deferred flush path (ISSUE
    18): ``None`` follows ``cfg.gossip.stamp_deferred``; an explicit
    True/False overrides it for A/B at a matched config (True on a
    per-round config models the max unit, ``STAMP_UNIT``).  Deferred,
    the per-learn-round stamp R+W becomes a once-per-cohort flush
    (``flush_stamp_pass`` / ``ops.fused_flush``) — amortized by the
    flush unit — plus the overlay fold+clear and cache recompute at the
    same cadence.  **Model convention** (the STATUS round-8 floor
    arithmetic): the mid-cohort learned-bit ORs (``overlay |=
    new_words``, ``sendable |= new_words``) ride the merge's fused
    elementwise word loop beside the ``known`` merge and are charged at
    the flush boundary where the overlay is actually folded into the
    stamp plane, not as separate per-round plane passes — the
    compiled-HLO cross-check carries whatever slack that convention
    hides, same as every other fusion assumption on the "xla" path.
    """
    if regime not in ("sustained", "active", "quiescent", "detection"):
        raise ValueError(f"unknown regime {regime!r}")
    if path not in KERNEL_PATHS:
        raise ValueError(f"unknown path {path!r} (one of {KERNEL_PATHS})")
    g: GossipConfig = cfg.gossip
    if stamp_deferred is None:
        stamp_deferred = g.stamp_deferred
    # the modeled flush cadence: the config's unit, or the max cohort
    # (STAMP_UNIT = one quarter) when deferral is forced onto a
    # per-round config for the A/B
    unit = float(g.stamp_flush_unit if g.stamp_deferred else STAMP_UNIT) \
        if stamp_deferred else 1.0
    n, k = g.n, g.k_facts
    w = g.words
    d = cfg.vivaldi.dimensionality

    stamp = float(n * g.stamp_cols)  # u8[N, K/2] packed (u8[N, K] A/B)
    known = float(n * w * 4)        # u32[N, W]
    overlay = known                 # u32[N, W] learned-since-flush bits
    alive = float(n)                # bool[N]
    vec = float(n * d * 4)          # f32[N, D]
    col = float(n * 4)              # one f32/i32 column
    pos = float(n * 3 * 4)          # f32[N, 3] hidden positions
    plane_sizes = {"stamp": stamp, "known": known, "packets": known,
                   "sendable": known, "alive": alive}
    if stamp_deferred:
        plane_sizes["overlay"] = overlay

    E: List[Entry] = []
    add = E.append

    gossip_on = regime in ("sustained", "active", "detection")
    learns = regime in ("sustained", "detection")

    # the sendable cache is valid exactly when the previous round's merge
    # learned something — i.e. (essentially) every round under sustained
    # load or a detection burst, and never in the no-learn "active"
    # window or quiescent state.  The standalone-kernel path never has a
    # valid cache (its merge invalidates).
    cache_hot = (g.use_sendable_cache and path != "kernels"
                 and regime in ("sustained", "detection"))

    if sustained_rate > 0 and regime in ("sustained", "detection"):
        # inject_facts_batch: retirement clears known bits everywhere
        # (R+W the word plane); the per-fact fact/stamp/sendable scatters
        # are O(m) cells (the cache mirror no longer pays a plane pass —
        # selection ANDs `known`, see GossipState.sendable_round)
        add(Entry("inject", "known", "RW", 2 * known, 1.0,
                  "dissemination.inject_facts_batch"))
        # tombstone fold at retirement: skip-gated on a retiring DEAD
        # fact — user-event churn never opens it, so the fold's coverage
        # gathers bill only the detection regime (below)

    if gossip_on:
        if cache_hot:
            # selection: alive-masked `sendable & known` — the stamp
            # plane is NOT touched (32 MB/round saved at 1M); the known
            # read is what masks stale cache bits for retired slots
            # (the trade that deleted inject's second plane pass).  THE
            # full-plane pass the fused family removes from the kernel
            # path: ops.fused_select_cached is word-plane-only.
            sel_where = ("ops.fused_select_cached" if path == "fused"
                         else "dissemination.select_phase cached")
            add(Entry("selection", "sendable", "R", known, 1.0,
                      sel_where))
            add(Entry("selection", "known", "R", known, 1.0,
                      sel_where + " (stale mask)"))
            add(Entry("selection", "alive", "R", alive, 1.0, sel_where))
        else:
            # selection fallback: sending_mask + pack — one fused read
            # pass over the stamp plane + known words + alive
            sel_where = ("ops.select_packets" if path != "xla"
                         else "dissemination.sending_mask")
            add(Entry("selection", "stamp", "R", stamp, 1.0, sel_where))
            add(Entry("selection", "known", "R", known, 1.0, sel_where))
            add(Entry("selection", "alive", "R", alive, 1.0, sel_where))
        add(Entry("selection", "packets", "W", known, 1.0,
                  "dissemination.select_phase pack" if path == "xla"
                  else "ops select kernel packets out"))
        # exchange (rotation): ONE doubled copy of packets (hoisted by
        # construction in exchange_phase and sliced per fanout via
        # rolled_rows(doubled=...)), then per-fanout a contiguous slice
        # read OR-accumulated into incoming.  Identical on every path —
        # the exchange is the separate (hookable, cross-chip) leg the
        # kernels never swallow.
        add(Entry("exchange", "packets", "RW", 3 * known, 1.0,
                  "dissemination.exchange_phase hoisted double"))
        add(Entry("exchange", "packets", "R",
                  known * g.fanout, 1.0,
                  "dissemination.exchange_phase slices"))
        add(Entry("exchange", "packets", "W", known, 1.0,
                  "dissemination.exchange_phase incoming accum"))
        # merge: one fused pass over incoming+known -> known
        merge_where = {"xla": "dissemination.merge_phase learn",
                       "kernels": "ops.merge_incoming",
                       "fused": "ops.fused_merge"}[path]
        add(Entry("merge", "known", "RW", 3 * known, 1.0, merge_where))
        if path != "xla":
            add(Entry("merge", "alive", "R", alive, 1.0, merge_where))
        # stamp learn pass: on the XLA path gated on learned_any (in the
        # sustained regime fresh facts spread every round so it runs);
        # the pallas kernels stream the stamp plane unconditionally
        # whenever the gossip gate is open (the learned_any cond gates
        # which OUTPUT buffers are kept, not the kernel's DMAs), so the
        # no-learn "active" regime pays it on the kernel paths.  The
        # wrap clamp AND (fused path) the sendable-cache recompute ride
        # the same streaming pass.
        if learns or path != "xla":
            if stamp_deferred:
                # ISSUE 18 quarter-deferred flushes: the stamp R+W runs
                # once per cohort (flush_stamp_pass / ops.fused_flush),
                # reading the overlay it retires and clearing it; the
                # cache recompute rides the same flush.  Mid-cohort
                # learned-bit ORs ride the merge word loop (module
                # convention, see round_traffic docstring).
                flush_where = ("ops.fused_flush" if path == "fused"
                               else "dissemination.flush_stamp_pass")
                add(Entry("merge", "stamp", "RW", 2 * stamp, 1.0 / unit,
                          flush_where + " per-cohort flush+clamp"))
                add(Entry("merge", "overlay", "RW", 2 * overlay,
                          1.0 / unit,
                          flush_where + " overlay fold + clear"))
                if g.use_sendable_cache and path != "kernels":
                    add(Entry("merge", "sendable", "W", known,
                              1.0 / unit,
                              flush_where + " cache recompute"))
            else:
                add(Entry("merge", "stamp", "RW", 2 * stamp, 1.0,
                          merge_where + " stamp+clamp"))
                if g.use_sendable_cache and path != "kernels":
                    add(Entry("merge", "sendable", "W", known, 1.0,
                              merge_where + " cache recompute"))

    if not learns and (path != "kernels" or not gossip_on):
        # standalone wraparound clamp: only fires when no stamp-
        # streaming pass has clamped for CLAMP_EVERY rounds — never
        # under sustained load or detection bursts; amortized in the
        # quiescent regime on every path.  In the no-learn active
        # window it fires on the XLA path AND the fused path (the fused
        # merge's learned_any cond DISCARDS the kernel's clamped stamp
        # output when nothing was learned, so last_clamp does not
        # advance); only the standalone kernels clamp-and-commit
        # in-stream every active round.
        add(Entry("clamp", "stamp", "RW", 2 * stamp,
                  1.0 / CLAMP_EVERY, "dissemination.clamp_stamps"))

    if cfg.with_failure:
        # probe sweep (round_robin rotation): alive rolls for target +
        # indirect helpers (each roll = concat 2n write + n read), the
        # drop masks, and the detection combine — all n-sized bool/word
        # passes.  Steady regimes: zero candidates, so _bounded_inject's
        # body is cond-skipped and only the any() reduce runs.
        ip = cfg.failure.indirect_probes
        rolls = 2 + ip                   # target, inverse, helpers
        add(Entry("probe", "alive", "RW", rolls * 3 * alive + 4 * alive,
                  1.0 / cfg.probe_every,
                  "failure.probe_round (round_robin)"))
        # refute/declare: gated by K-sized predicates in all steady
        # regimes (accusations_pending / live_suspicions) — O(K) only.
        # In the DETECTION regime those gates are open and the bodies'
        # plane scans + bounded injections run:
        if regime == "detection":
            # refute: accusation scan over the unpacked known plane
            add(Entry("refute", "known", "R", known, 1.0,
                      "failure.refute_round body"))
            # declare: the expiry scan derives q-ages — a full
            # stamp-plane read, now HALVED (packed) and riding the probe
            # cadence (cluster_round gates declare on probe_tick)
            add(Entry("declare", "stamp", "R", stamp,
                      1.0 / cfg.probe_every,
                      "failure._declare_round_body mod_age scan"))
            if stamp_deferred:
                # deferred: the expiry scan masks pending overlay
                # learns (q-age 0, never expired) — one extra word-
                # plane read beside the stamp scan
                add(Entry("declare", "overlay", "R", overlay,
                          1.0 / cfg.probe_every,
                          "failure._declare_round_body overlay mask"))
            add(Entry("declare", "known", "R", known,
                      1.0 / cfg.probe_every,
                      "failure._declare_round_body"))
            # up to three bounded injections: refute's alive-inject runs
            # every round; the suspect (probe) and dead (declare)
            # injections ride the probe cadence — pick_bounded score
            # passes + batch scatters + retirement passes
            inj_known = 2 * known
            add(Entry("detect-inj", "known", "RW",
                      3 * (inj_known + 4 * n + 3 * alive),
                      (1.0 + 2.0 / cfg.probe_every) / 3.0,
                      "failure._bounded_inject x3 (2 on probe cadence)"))
            # tombstone fold: detection bursts retire dead facts, which
            # opens the skip-gate — m known-plane COLUMN gathers (u32
            # words, 4 bytes/cell) + alive reads + the bool[N] plane R+W
            add(Entry("detect-inj", "tombstone", "RW",
                      sustained_rate * 4 * n + 3 * alive, 1.0,
                      "dissemination.inject_facts_batch tombstone fold"))

    if cfg.push_pull_every > 0:
        # partner roll of known (concat + slice) + merge pass; stamp
        # learn pass gated on learned_any (runs when partners differ —
        # the sustained regime; skipped when converged)
        pp_bytes = 3 * known + 3 * known + 3 * alive
        if learns:
            if g.use_sendable_cache:
                pp_bytes += 2 * known   # sendable OR of the learn bits
            if stamp_deferred:
                # no stamp pass at all: the sync's learns ride the
                # overlay (antientropy deferred branch) and the next
                # cohort flush retires them.  With the cache on, the
                # overlay OR shares the cache OR's fused word loop
                # (module convention — same new_words operand); cache
                # off it is the only plane OR and is charged
                if not g.use_sendable_cache:
                    pp_bytes += 2 * overlay
            else:
                add(Entry("push_pull", "stamp", "RW", 2 * stamp,
                          1.0 / cfg.push_pull_every,
                          "antientropy.push_pull_round stamp+clamp"))
        add(Entry("push_pull", "known", "RW", pp_bytes,
                  1.0 / cfg.push_pull_every,
                  "antientropy.push_pull_round"))

    if cfg.with_vivaldi:
        # one spring update per probe tick: vec R+W, scalar cols
        # (height/error/adjustment/adj_sum, rtt gathers), the adj_samples
        # ring COLUMN (incremental, not the window plane), positions read
        # for self + rolled partner (concat)
        viv = 2 * vec + 8 * col + 2 * col + (3 * pos) + 2 * alive
        add(Entry("vivaldi", "vivaldi", "RW", viv,
                  1.0 / cfg.probe_every, "vivaldi.vivaldi_update"))

    return TrafficReport(n=n, k=k, regime=regime, entries=E, path=path,
                         plane_sizes=plane_sizes)


def kernel_path_summary(cfg, regime: str = "sustained",
                        sustained_rate: int = 2) -> dict:
    """The fused-round comparison artifact (ISSUE 7): per dispatch path,
    the modeled bytes/round, the per-plane full-plane pass counts, and
    the reductions the fused family delivers.  The honest headline
    numbers:

    - fused vs the standalone kernel path: the selection's full
      stamp-plane read is REMOVED (the cache is maintained in-kernel),
      so the packed stamp plane is streamed strictly fewer times per
      round.
    - fused vs the XLA model of record: byte PARITY (±alive column) —
      the fused kernels turn the XLA path's fusion ASSUMPTIONS (which
      the compiled-HLO cross-check measures as real slack,
      ``hlo_bytes_per_round``) into construction guarantees: every pass
      is one authored DMA stream.

    The ≥2x-vs-the-233.4-pin aspiration is NOT reachable under strict
    per-round bit-exactness and is documented with its floor arithmetic
    in STATUS.md: exchange (separate hookable leg) + the merge's
    known/incoming words + the per-learn-round stamp R+W +
    probe/push-pull/vivaldi already exceed half the pin.  ISSUE 18
    pulled the remaining lever: quarter-deferred stamp flushes
    (``GossipConfig.stamp_flush_unit``) — a deliberate semantics change
    (stamps stale up to 3 rounds mid-cohort, every mod_age reader
    amended by the overlay) that breaks the 217 floor on a deferred
    config (``round_traffic(..., stamp_deferred=True)`` prices it;
    bench's ``stamp_flush_ab`` carries the A/B).  This summary prices
    the config as given — pass a deferred config to see the broken
    floor per path.
    """
    out = {"regime": regime, "paths": {}}
    for path in KERNEL_PATHS:
        r = round_traffic(cfg, regime=regime,
                          sustained_rate=sustained_rate, path=path)
        out["paths"][path] = {
            "total_bytes": r.total_bytes,
            "by_plane": r.by_plane(),
            "passes_by_plane": {p: round(v, 3)
                                for p, v in r.passes_by_plane().items()},
            "ceiling_rps": round(r.ceiling_rounds_per_sec(), 1),
        }
    kern = out["paths"]["kernels"]
    fused = out["paths"]["fused"]
    out["fused_vs_kernels"] = {
        "bytes_saved": kern["total_bytes"] - fused["total_bytes"],
        "reduction_factor": round(
            kern["total_bytes"] / fused["total_bytes"], 4),
        "stamp_passes_removed": round(
            kern["passes_by_plane"].get("stamp", 0.0)
            - fused["passes_by_plane"].get("stamp", 0.0), 3),
    }
    out["fused_vs_xla"] = {
        "bytes_delta": (fused["total_bytes"]
                        - out["paths"]["xla"]["total_bytes"]),
        "note": "parity by construction: authored DMA streams vs "
                "assumed XLA fusion (hlo_bytes_per_round measures the "
                "assumption's real slack)",
    }
    return out


def telemetry_leg_traffic(cfg, n_devices: int = 8) -> dict:
    """Byte/ICI model of the in-collective telemetry legs
    (``parallel.ring.round_telemetry_sharded``) — the arithmetic behind
    the ~0-extra-bytes claim (ISSUE 15 / ROADMAP item 4 in-network
    aggregation): the per-round cluster row costs **O(fields)** bytes
    per chip at ANY node count, vs the O(N)-plane gather the same row
    would otherwise require.

    The three legs and their payloads (K = ``k_facts``):

    - ``pmax`` subject-incarnation assembly: u32[K]  (4K bytes)
    - fused ``psum`` stage-1 partials:       i32[1 + 2K]
    - ``psum`` false-DEAD scalar:            i32[1]

    Each all-reduce of ``p`` payload bytes moves ~``2 p (D-1)/D`` bytes
    per chip (reduce-scatter + all-gather decomposition).  The gathered
    alternative is priced as the N-planes the row actually reads
    (known + stamp + alive + incarnation + tombstone) landing on one
    chip — what a naive ``device_get``/gather implementation ships.

    Returns a dict with both sides and their ratio; the pinned test
    (tests/test_accounting.py) holds the leg bytes independent of ``n``
    and ≤ a per-mille of the exchange block."""
    g: GossipConfig = cfg.gossip
    n, k, w, d = g.n, g.k_facts, g.words, max(1, n_devices)
    payloads = {
        "pmax_subject_incarnations": 4 * k,
        "psum_stage1_partials": 4 * (1 + 2 * k),
        "psum_false_dead": 4,
    }
    factor = 2.0 * (d - 1) / d
    per_leg = {name: factor * p for name, p in payloads.items()}
    total = sum(per_leg.values())
    stamp_plane = float(n * (k // 2 if g.pack_stamp else k))
    gathered = (d - 1) / d * float(
        n * w * 4          # known bitset u32[N, W]
        + stamp_plane      # stamp plane u8
        + n                # alive bool[N]
        + n * 4            # incarnation u32[N]
        + n)               # tombstone bool[N]
    return {
        "n": n, "n_devices": d, "k_facts": k,
        "payload_bytes": payloads,
        "bytes_per_chip_per_round": total,
        "per_leg_bytes_per_chip": per_leg,
        "ici_us": total / V5E_ICI_BYTES_PER_S * 1e6,
        "collective_launches": 3,
        "gathered_alternative_bytes_per_chip": gathered,
        "fraction_of_gather": total / gathered if gathered else 0.0,
        "rule": "payloads are O(k_facts), never O(n): the row rides the "
                "exchange collective as fused psum/pmax legs — "
                "cluster-wide observability at ~0 extra bytes at any D",
    }


def propagation_split(cfg, regime: str = "sustained",
                      sustained_rate: int = 2, path: str = "xla",
                      measured_redundancy: Optional[float] = None) -> dict:
    """The useful-vs-redundant byte split of the flagship round floor
    (ISSUE 16): extend the comm-cost decomposition from bytes-by-phase
    to bytes-by-*usefulness*.

    The dissemination leg (selection + exchange + merge — the phases
    that exist to move facts) re-ships each fact from every knower for
    ``transmit_window_rounds`` rounds at ``fanout`` reads per round,
    while each receiver learns it exactly once: the analytic useful
    fraction is ``1/(window · fanout)``
    (``obs.propagation.analytic_redundancy``), ~1.2% at the 1M flagship
    — the ~217 MB/round floor is overwhelmingly epidemic re-teaching,
    which is the redundancy robustness is paid for.  The split prices
    exactly that: how many of the floor's bytes taught someone
    something, judged against the device tracer's MEASURED cumulative
    redundancy when one is passed (``run_cluster_sustained(...,
    collect_propagation=True)``).

    Returns the dissemination/other byte decomposition, the analytic
    and effective useful fractions, and the resulting byte split of the
    full round total."""
    from serf_tpu.obs.propagation import analytic_redundancy

    g: GossipConfig = cfg.gossip
    report = round_traffic(cfg, regime=regime,
                           sustained_rate=sustained_rate, path=path)
    by_phase = report.by_phase()
    dissemination_phases = ("selection", "exchange", "merge")
    diss_bytes = sum(by_phase.get(p, 0.0) for p in dissemination_phases)
    other_bytes = report.total_bytes - diss_bytes
    analytic = analytic_redundancy(g.transmit_window_rounds, g.fanout)
    redundancy = (analytic if measured_redundancy is None
                  else float(measured_redundancy))
    useful_frac = 1.0 - redundancy
    return {
        "n": g.n, "k_facts": g.k_facts, "regime": regime, "path": path,
        "total_bytes": report.total_bytes,
        "dissemination_bytes": diss_bytes,
        "other_bytes": other_bytes,
        "by_phase": {p: by_phase.get(p, 0.0)
                     for p in dissemination_phases},
        "analytic_redundancy": analytic,
        "redundancy": redundancy,
        "redundancy_source": ("measured" if measured_redundancy
                              is not None else "analytic"),
        "useful_bytes": diss_bytes * useful_frac,
        "redundant_bytes": diss_bytes * redundancy,
        "rule": "useful fraction of the dissemination leg is "
                "1/(transmit_window_rounds x fanout): each knower "
                "re-ships a fact for the whole transmit window at "
                "`fanout` reads per round, each receiver learns it "
                "once — the epidemic floor is re-teaching by design",
    }


def ici_round_traffic(cfg, n_devices: int = 8) -> dict:
    """Per-phase, per-chip byte attribution for one flagship round under
    node sharding — the arithmetic behind the 8-chip throughput claim
    AND the ring-vs-all-gather schedule decision (ISSUE 6: the CPU
    virtual mesh measures collective *schedule shape*, not ICI
    bandwidth, so the decision is settled here, on the α-β model of
    "A Model for Communication in Clusters of Multi-core Machines").

    Legacy whole-round schedules (kept for STATUS.md continuity):

    - ``rotation`` (minimal-traffic bound): each of the ``fanout``
      rolled reads shifts the packed packet plane by a global offset, so
      a chip's rolled block arrives from (at most two) offset-neighbor
      chips — bytes/chip ≈ fanout × the local packet block.  The probe /
      vivaldi / push_pull rolls move N-sized columns at their cadences.
    - ``iid_allgather`` / ``iid_ring``: the full-plane materialization
      vs D-1 ppermute hops of the local block — same wire totals, peak
      HBM and overlap differ.

    New (the flagship sharded round, ``parallel.ring``):

    - ``per_phase_per_chip``: every round phase's HBM bytes/chip (the
      sustained model split D ways — every plane is node-sharded) plus
      its ICI bytes/chip, with the exchange leg priced under BOTH
      explicit schedules.
    - ``schedule``: the α-β decision.  Both schedules ship (D-1)×block
      per chip; the ring pays (D-1) collective launches but overlaps
      each hop's transfer with the previous hop's resolve and keeps peak
      HBM at 2 blocks; the all-gather pays one launch but materializes
      the full plane — an extra write+read of D blocks through HBM.
      Ring is recommended once that extra HBM round-trip outweighs the
      extra (D-2) launches: ``2·D·block/HBM_BW > (D-2)·α``.

    Returns a dict of bytes/chip/round plus derived μs at v5e bandwidths
    and the implied D-chip sustained ceiling.
    """
    g: GossipConfig = cfg.gossip
    n, w, d = g.n, g.words, n_devices
    packets_plane = float(n * w * 4)            # u32[N, W] packed packets
    block = packets_plane / d                   # one chip's shard

    rot_gossip = g.fanout * block               # fanout rolled block reads
    # push_pull: known-plane roll at its cadence
    pp_ici = ((packets_plane / d) / max(cfg.push_pull_every, 1)
              if cfg.push_pull_every > 0 else 0.0)
    probe_ici = 0.0
    if cfg.with_failure:
        # probe rolls: N-sized liveness columns per probe tick
        probe_ici = ((2 + cfg.failure.indirect_probes) * n / d
                     ) / cfg.probe_every
    viv_ici = 0.0
    if cfg.with_vivaldi:
        # vivaldi partner rolls (positions f32[N,3] + liveness) ride the
        # probe cadence (cluster_round wires them to probe_tick)
        viv_ici = ((3 * 4 * n + 4 * n) / d) / cfg.probe_every
    rot_aux = pp_ici + probe_ici + viv_ici
    rotation = rot_gossip + rot_aux

    allgather = (d - 1) / d * packets_plane     # the rest of the plane in
    ring = (d - 1) * block                      # D-1 hops of the block

    report = round_traffic(cfg, regime="sustained")
    hbm_per_chip = report.total_bytes / d
    out = {
        "n": n, "n_devices": d,
        "rotation_bytes_per_chip": rotation,
        "iid_allgather_bytes_per_chip": allgather,
        "iid_ring_bytes_per_chip": ring,
        "hbm_bytes_per_chip_sustained": hbm_per_chip,
        "rotation_ici_us": rotation / V5E_ICI_BYTES_PER_S * 1e6,
        "allgather_ici_us": allgather / V5E_ICI_BYTES_PER_S * 1e6,
        "hbm_us_per_chip": hbm_per_chip / V5E_HBM_BYTES_PER_S * 1e6,
    }

    # per-phase, per-chip attribution: HBM from the sustained model
    # (node-sharded planes split D ways), ICI from the collective leg
    # each phase actually runs on the sharded flagship round
    exchange_ici = {"ring": (d - 1) * block, "allgather": (d - 1) * block}
    phase_ici = {"exchange": exchange_ici["ring"], "push_pull": pp_ici,
                 "probe": probe_ici, "vivaldi": viv_ici}
    per_phase = {}
    for phase, nbytes in report.by_phase().items():
        per_phase[phase] = {
            "hbm_bytes_per_chip": nbytes / d,
            "ici_bytes_per_chip": phase_ici.get(phase, 0.0),
        }
    per_phase.setdefault("exchange", {"hbm_bytes_per_chip": 0.0,
                                      "ici_bytes_per_chip": 0.0})
    per_phase["exchange"].update({
        "ici_bytes_per_chip_ring": exchange_ici["ring"],
        "ici_bytes_per_chip_allgather": exchange_ici["allgather"],
        # peak HBM held by the leg: ring keeps the resident block + the
        # visiting block; all-gather materializes the whole plane next
        # to the local block
        "peak_hbm_bytes_ring": 2 * block,
        "peak_hbm_bytes_allgather": packets_plane + block,
        "collective_launches_ring": d - 1,
        "collective_launches_allgather": 1,
    })
    out["per_phase_per_chip"] = per_phase

    # the α-β schedule decision (module docstring): wire bytes tie, so
    # ring wins exactly when the all-gather's extra HBM round-trip of
    # the materialized plane costs more than the ring's extra launches
    ring_alpha_s = (d - 1) * ICI_HOP_ALPHA_S
    ag_alpha_s = ICI_HOP_ALPHA_S
    ag_extra_hbm = 2.0 * packets_plane          # write + read the plane
    ring_us = (ring_alpha_s + ring / V5E_ICI_BYTES_PER_S) * 1e6
    ag_us = (ag_alpha_s + allgather / V5E_ICI_BYTES_PER_S
             + ag_extra_hbm / V5E_HBM_BYTES_PER_S) * 1e6
    out["schedule"] = {
        "ring": {"ici_us": ring_us, "launches": d - 1,
                 "peak_hbm_bytes": 2 * block, "extra_hbm_bytes": 0.0},
        "allgather": {"ici_us": ag_us, "launches": 1,
                      "peak_hbm_bytes": packets_plane + block,
                      "extra_hbm_bytes": ag_extra_hbm},
        "recommended": "ring" if ring_us <= ag_us else "allgather",
        "rule": "wire bytes tie at (D-1)*block; ring wins once the "
                "all-gather's full-plane HBM round-trip (2*D*block/"
                "HBM_BW) exceeds the ring's extra (D-2) collective "
                "launches — i.e. at flagship scale; allgather wins at "
                "small blocks where launch latency dominates",
    }

    # the in-collective telemetry legs (ISSUE 15): O(fields) bytes per
    # chip beside the exchange's O(N/D) packet blocks — priced here so
    # the ~0-extra-bytes claim is part of the same per-phase attribution
    out["telemetry"] = telemetry_leg_traffic(cfg, d)

    # the round is bound by the slower of HBM and ICI (they overlap at
    # best); the implied D-chip sustained ceiling uses the rotation path
    bound_s = max(out["rotation_ici_us"], out["hbm_us_per_chip"]) / 1e6
    out["implied_sustained_ceiling_rps"] = 1.0 / bound_s if bound_s else 0.0
    return out


def hlo_bytes_per_round(jitted, *args, num_rounds: int,
                        **kwargs) -> Optional[float]:
    """Compiled-HLO cross-check: XLA's own bytes-accessed estimate per
    round for a jitted ``run_*(state, key=..., num_rounds=...)`` driver.
    Returns None if the backend exposes no cost analysis."""
    compiled = jitted.lower(*args, num_rounds=num_rounds,
                            **kwargs).compile()
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent surface
        return None
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    total = ca.get("bytes accessed")
    if total is None:
        return None
    return float(total) / num_rounds


def emit_traffic_metrics(report: TrafficReport, labels=None) -> dict:
    """Emit the analytic HBM traffic model as gauges onto the process
    sink: modeled bytes/round per plane (label ``plane=...``), the total,
    and the single-chip bandwidth-ceiling rounds/sec.  Operators (and
    ``Serf.stats()`` consumers) can then compare the model against the
    measured ``serf.device.dispatch-ms`` timings without re-deriving it.
    """
    from serf_tpu.utils import metrics

    vals = {}
    for plane, nbytes in report.by_plane().items():
        metrics.gauge("serf.model.traffic.plane-bytes", nbytes,
                      dict(labels or {}, plane=plane))
        vals[f"serf.model.traffic.plane-bytes{{plane={plane}}}"] = nbytes
    vals["serf.model.traffic.bytes-per-round"] = report.total_bytes
    vals["serf.model.traffic.ceiling-rps"] = report.ceiling_rounds_per_sec()
    metrics.gauge("serf.model.traffic.bytes-per-round",
                  report.total_bytes, labels)
    metrics.gauge("serf.model.traffic.ceiling-rps",
                  report.ceiling_rounds_per_sec(), labels)
    return vals
