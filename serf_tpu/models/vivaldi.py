"""Device-plane Vivaldi: N×8 coordinate estimation co-trained with gossip.

Vectorizes the host CoordinateClient math (serf_tpu/host/coordinate.py; the
scalar parity oracle for reference serf-core/src/types/coordinate.rs) over
every node at once: per round, each node takes one RTT observation against
its gossip/probe partner and applies the error-weighted spring relaxation,
rolling adjustment, and gravity — pure elementwise f32 math that XLA fuses
into a handful of kernels.  Baseline config #5 (BASELINE.json): 1M-node
latency-graph estimation.

Latency filtering (round 4): the reference's per-PEER median filter
would need O(N²) state at cluster scale; ``VivaldiConfig.
latency_filter_size`` instead gives an optional per-NODE median ring
over the partner sample stream (O(N) state, all elementwise).  Default
1 (off) — on a clean stream cross-partner mixing corrupts the
(partner, rtt) pairing; under spiked RTT noise the filter measurably
wins (test pinned).  The parity test pins device-vs-host equality at
``latency_filter_size=1``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

ZERO_THRESHOLD = 1.0e-9


@dataclasses.dataclass(frozen=True)
class VivaldiConfig:
    """Defaults match the reference (coordinate.rs:52-204)."""

    dimensionality: int = 8
    error_max: float = 1.5
    ce: float = 0.25
    cc: float = 0.25
    adjustment_window: int = 20
    height_min: float = 10.0e-6
    gravity_rho: float = 150.0
    #: per-NODE median filter over the last F observed RTT samples
    #: (f32[N, F] — 12 MB at 1M for F=3).  The reference filters per-PEER
    #: (coordinate.rs latency filter, default 3), which is O(N²) state at
    #: cluster scale; this per-node variant filters the rotation-partner
    #: sample STREAM instead, rejecting transport spikes (the filter's
    #: purpose) at the cost of mixing samples across partners.  Default 1
    #: (off): on a clean RTT stream cross-partner mixing corrupts the
    #: (partner, rtt) pairing the spring update needs; enable (3) for
    #: noisy environments — test_vivaldi_latency_filter_rejects_spikes
    #: quantifies the trade.  Must be <= adjustment_window: the ring
    #: cursor rides adj_index, which wraps at the window (validated in
    #: __post_init__).
    latency_filter_size: int = 1

    def __post_init__(self):
        if not 1 <= self.latency_filter_size <= self.adjustment_window:
            raise ValueError(
                f"latency_filter_size {self.latency_filter_size} must be in "
                f"[1, adjustment_window={self.adjustment_window}] — the "
                f"ring cursor rides adj_index, which wraps at the window")


class VivaldiState(NamedTuple):
    vec: jnp.ndarray          # f32[N, D]
    height: jnp.ndarray       # f32[N]
    error: jnp.ndarray        # f32[N]
    adjustment: jnp.ndarray   # f32[N]
    adj_samples: jnp.ndarray  # f32[N, window] rolling rtt-dist samples
    adj_sum: jnp.ndarray      # f32[N] running sum of adj_samples rows —
                              # updated incrementally (one column read, not
                              # an 80 MB full-window reduce per round at
                              # 1M); re-summed exactly at each ring wrap so
                              # f32 drift is bounded to `window` updates
    adj_index: jnp.ndarray    # i32 scalar ring cursor
    rtt_ring: jnp.ndarray     # f32[N, F] recent raw RTT samples (median
                              # latency filter; F=1 plane unused)
    rtt_seen: jnp.ndarray     # bool[N] ring seeded by a first sample


def make_vivaldi(n: int, cfg: VivaldiConfig) -> VivaldiState:
    return VivaldiState(
        vec=jnp.zeros((n, cfg.dimensionality), jnp.float32),
        height=jnp.full((n,), cfg.height_min, jnp.float32),
        error=jnp.full((n,), cfg.error_max, jnp.float32),
        adjustment=jnp.zeros((n,), jnp.float32),
        adj_samples=jnp.zeros((n, cfg.adjustment_window), jnp.float32),
        adj_sum=jnp.zeros((n,), jnp.float32),
        adj_index=jnp.asarray(0, jnp.int32),
        rtt_ring=jnp.zeros((n, max(1, cfg.latency_filter_size)),
                           jnp.float32),
        rtt_seen=jnp.zeros((n,), bool),
    )


def _raw_distance(vec_a, h_a, vec_b, h_b):
    return jnp.linalg.norm(vec_a - vec_b, axis=-1) + h_a + h_b


def estimated_rtt(state: VivaldiState, i, j) -> jnp.ndarray:
    """Adjusted distance estimate between node indices (vectorized)."""
    dist = _raw_distance(state.vec[i], state.height[i],
                         state.vec[j], state.height[j])
    adjusted = dist + state.adjustment[i] + state.adjustment[j]
    return jnp.where(adjusted > 0.0, adjusted, dist)


def _unit_vectors(diff: jnp.ndarray, key: jax.Array):
    """Unit vectors along ``diff`` rows; random directions where coincident
    (reference coordinate.rs apply_force)."""
    mag = jnp.linalg.norm(diff, axis=-1)
    rnd = jax.random.uniform(key, diff.shape) - 0.5
    rnd_mag = jnp.maximum(jnp.linalg.norm(rnd, axis=-1), ZERO_THRESHOLD)
    coincident = mag <= ZERO_THRESHOLD
    unit = jnp.where(coincident[:, None], rnd / rnd_mag[:, None],
                     diff / jnp.maximum(mag, ZERO_THRESHOLD)[:, None])
    return unit, jnp.where(coincident, 0.0, mag)


def vivaldi_update(state: VivaldiState, cfg: VivaldiConfig,
                   peer: jnp.ndarray, rtt: jnp.ndarray,
                   key: jax.Array, active=None,
                   peer_roll=None) -> VivaldiState:
    """One observation per node: node i measured ``rtt[i]`` against
    ``peer[i]``.  Nodes with ``active[i]=False`` keep their state.

    Faithful vectorization of CoordinateClient.update (host plane), which is
    itself the reference's update path (coordinate.rs:727-762 + gravity
    699-705): vivaldi force -> adjustment window -> gravity.

    ``peer_roll``: when the caller sampled peers as one rotation
    (``peer[i] = (i + peer_roll) % n``, GossipConfig.peer_sampling
    "rotation"), pass the offset so peer state is read with contiguous
    rolls instead of 1M-row gathers (serial-loop scatter/gather cost on
    TPU).  In that mode ``peer`` is unused and may be None.
    """
    n = state.vec.shape[0]
    if active is None:
        active = jnp.ones((n,), bool)
    k_force, k_grav = jax.random.split(key)
    rtt = jnp.maximum(rtt, ZERO_THRESHOLD)

    # -- optional per-node median latency filter (see VivaldiConfig)
    fsize = cfg.latency_filter_size
    if fsize > 1:
        # the first active sample seeds the whole ring (median of fewer-
        # than-F observed samples ≈ the host filter's warmup); later
        # actives overwrite one slot under a shared cursor — a stale slot
        # still holds this node's own older sample.  All elementwise over
        # [N, F]: no scatters.
        seed = (~state.rtt_seen & active)[:, None]
        ring = jnp.where(seed, rtt[:, None], state.rtt_ring)
        col = state.adj_index % fsize
        onehot = (jnp.arange(fsize) == col)[None, :]
        ring = jnp.where(onehot & (state.rtt_seen & active)[:, None],
                         rtt[:, None], ring)
        rtt = jnp.where(active, jnp.median(ring, axis=1), rtt)
        rtt_seen = state.rtt_seen | active
    else:
        ring, rtt_seen = state.rtt_ring, state.rtt_seen

    if peer_roll is None:
        p_vec = state.vec[peer]
        p_h = state.height[peer]
        p_err = state.error[peer]
        p_adj = state.adjustment[peer]
    else:
        from serf_tpu.models.dissemination import rolled_rows
        p_vec = rolled_rows(state.vec, peer_roll)
        p_h = rolled_rows(state.height, peer_roll)
        p_err = rolled_rows(state.error, peer_roll)
        p_adj = rolled_rows(state.adjustment, peer_roll)

    # -- vivaldi spring relaxation (adjustment-inclusive distance, matching
    # the host oracle / reference distance_to semantics)
    raw = _raw_distance(state.vec, state.height, p_vec, p_h)
    adjusted = raw + state.adjustment + p_adj
    dist = jnp.where(adjusted > 0.0, adjusted, raw)
    wrongness = jnp.abs(dist - rtt) / rtt
    total_err = jnp.maximum(state.error + p_err, ZERO_THRESHOLD)
    weight = state.error / total_err
    error = jnp.minimum(
        state.error * (1.0 - cfg.ce * weight) + wrongness * cfg.ce * weight,
        cfg.error_max)
    force = cfg.cc * weight * (rtt - dist)
    unit, mag = _unit_vectors(state.vec - p_vec, k_force)
    vec = state.vec + unit * force[:, None]
    height = jnp.where(
        mag > 0.0,
        jnp.maximum(cfg.height_min,
                    (state.height + p_h) * force / jnp.maximum(mag, ZERO_THRESHOLD)
                    + state.height),
        state.height)

    # -- adjustment term (recomputed against the post-force position).
    # Only ONE window column changes per round, so the rolling window is
    # maintained with a column read + column write + running-sum update
    # instead of a full-plane select and reduce (the f32[N, 20] plane is
    # 80 MB at 1M nodes — reading and rewriting it every round was the
    # single biggest HBM consumer in the vivaldi phase).
    dist2 = _raw_distance(vec, height, p_vec, p_h)
    sample = rtt - dist2
    idx = state.adj_index % cfg.adjustment_window
    old_col = jax.lax.dynamic_slice_in_dim(state.adj_samples, idx, 1,
                                           axis=1)[:, 0]
    new_col = jnp.where(active, sample, old_col)
    adj_samples = jax.lax.dynamic_update_slice_in_dim(
        state.adj_samples, new_col[:, None], idx, axis=1)
    adj_sum = state.adj_sum - old_col + new_col
    adjustment = adj_sum / (2.0 * cfg.adjustment_window)

    # -- gravity toward the origin (adjustment-inclusive from the origin's
    # viewpoint: origin adjustment is 0, ours applies)
    origin_raw = jnp.linalg.norm(vec, axis=-1) + height + cfg.height_min
    origin_adj = origin_raw + adjustment
    origin_dist = jnp.where(origin_adj > 0.0, origin_adj, origin_raw)
    g_force = -1.0 * (origin_dist / cfg.gravity_rho) ** 2
    g_unit, g_mag = _unit_vectors(vec, k_grav)
    g_vec = vec + g_unit * g_force[:, None]
    g_height = jnp.where(
        g_mag > 0.0,
        jnp.maximum(cfg.height_min,
                    (height + cfg.height_min) * g_force
                    / jnp.maximum(g_mag, ZERO_THRESHOLD) + height),
        height)

    # -- NaN/Inf safety: reset invalid rows (reference validity check)
    cand = VivaldiState(g_vec, g_height, error, adjustment, adj_samples,
                        adj_sum,
                        (state.adj_index + 1) % cfg.adjustment_window,
                        ring, rtt_seen)
    bad = ~(jnp.all(jnp.isfinite(cand.vec), axis=-1)
            & jnp.isfinite(cand.height) & jnp.isfinite(cand.error)
            & jnp.isfinite(cand.adjustment))
    fresh = make_vivaldi(n, cfg)
    act = active & ~bad
    reset = bad & active          # rows to wipe to fresh state
    any_reset = jnp.any(reset)

    def pick(new, old, fresh_arr):
        if new.ndim == 0:
            return new
        mask = act if new.ndim == 1 else act[:, None]
        out = jnp.where(mask, new, old)
        # the bad-row wipe is a second full-plane select per field: ride
        # a lax.cond so the (overwhelmingly common) all-finite round
        # pays only the first
        rmask = reset if new.ndim == 1 else reset[:, None]
        return jax.lax.cond(
            any_reset,
            lambda o: jnp.where(rmask, fresh_arr, o),
            lambda o: o,
            out)

    # adj_samples needs no act-select (inactive rows already kept their
    # old column above); same single reset mask/predicate as pick()
    adj_samples_f = jax.lax.cond(
        any_reset,
        lambda s: jnp.where(reset[:, None], 0.0, s),
        lambda s: s,
        cand.adj_samples)

    # exact re-sum once per window wrap — AFTER the active/bad routing, so
    # it corrects ALL rows (inactive rows' unchanged samples and reset
    # rows' zeros sum exactly too): incremental f32 drift in the carried
    # adj_sum is bounded to one window of updates.  Rides a lax.cond so
    # the full-plane reduce costs 1/window of the rounds.
    adj_sum_f = pick(cand.adj_sum, state.adj_sum, fresh.adj_sum)
    adj_sum_f = jax.lax.cond(
        idx == cfg.adjustment_window - 1,
        lambda s: jnp.sum(s, axis=1),
        lambda s: adj_sum_f,
        adj_samples_f)

    return VivaldiState(
        vec=pick(cand.vec, state.vec, fresh.vec),
        height=pick(cand.height, state.height, fresh.height),
        error=pick(cand.error, state.error, fresh.error),
        adjustment=pick(cand.adjustment, state.adjustment, fresh.adjustment),
        adj_samples=adj_samples_f,
        adj_sum=adj_sum_f,
        adj_index=cand.adj_index,
        # ring rows already route inactive nodes to their old samples;
        # bad-row wipe matches the fresh state (re-seeded on next sample).
        # At fsize == 1 the planes are semantically unused — pass them
        # through untouched so the round pays nothing for them.
        rtt_ring=(pick(cand.rtt_ring, state.rtt_ring, fresh.rtt_ring)
                  if fsize > 1 else state.rtt_ring),
        rtt_seen=(pick(cand.rtt_seen, state.rtt_seen, fresh.rtt_seen)
                  if fsize > 1 else state.rtt_seen),
    )


def ground_truth_rtt(positions: jnp.ndarray, i, j,
                     base: float = 0.005) -> jnp.ndarray:
    """Synthetic latency graph: euclidean distance over hidden positions
    plus a base propagation delay (the '1M-node latency graph' of baseline
    config #5)."""
    return base + jnp.linalg.norm(positions[i] - positions[j], axis=-1)


def ground_truth_rtt_rolled(positions: jnp.ndarray, shift,
                            base: float = 0.005) -> jnp.ndarray:
    """``ground_truth_rtt(positions, i, (i+shift)%n)`` for all i, with the
    peer read as a contiguous roll (rotation peer sampling)."""
    from serf_tpu.models.dissemination import rolled_rows
    return base + jnp.linalg.norm(
        positions - rolled_rows(positions, shift), axis=-1)


def mean_relative_error(state: VivaldiState, cfg: VivaldiConfig,
                        positions: jnp.ndarray, key: jax.Array,
                        samples: int = 4096) -> jnp.ndarray:
    """Estimation quality: mean |est-true|/true over random pairs."""
    n = state.vec.shape[0]
    k1, k2 = jax.random.split(key)
    i = jax.random.randint(k1, (samples,), 0, n)
    j = jax.random.randint(k2, (samples,), 0, n)
    est = estimated_rtt(state, i, j)
    true = ground_truth_rtt(positions, i, j)
    return jnp.mean(jnp.abs(est - true) / jnp.maximum(true, 1e-9))


def emit_vivaldi_metrics(state: VivaldiState, labels=None) -> dict:
    """Emit device-plane Vivaldi coordinate gauges onto the process sink.

    Same pull-based contract as ``emit_gossip_metrics``: one
    device->host sync of population means, call between scans — the
    device analog of the host plane's per-sample
    ``serf.coordinate.adjustment-ms`` observations.
    """
    from serf_tpu.utils import metrics

    # one device_get for the whole dict (see emit_gossip_metrics)
    vals = jax.device_get({
        "serf.model.vivaldi.error": jnp.mean(state.error),
        "serf.model.vivaldi.height": jnp.mean(state.height),
        "serf.model.vivaldi.adjustment": jnp.mean(state.adjustment),
    })
    vals = {name: float(v) for name, v in vals.items()}
    for name, v in vals.items():
        metrics.gauge(name, v, labels)
    return vals
