"""Poisson churn: leave/fail/rejoin processes driving the device cluster.

Baseline config #3 ("100k nodes, Poisson churn") and the richer scenario
library the reference exercises through shutdown/restart tests
(SURVEY.md §4; serf-core/src/serf/base/tests/serf.rs:163-258).  Per-round,
per-node event probabilities are Poisson thinning: with per-round rate λ a
node fires with p = 1−e^{−λ} ≈ λ for the small rates churn uses.

Event kinds:

- **fail**: the node crashes silently — no announcement; the SWIM failure
  detector must notice (probe → suspect → declare).
- **leave**: graceful — the node announces a ``K_LEAVE`` intent fact (the
  device analog of the reference's LeaveMessage broadcast,
  base.rs:1442-1572), participates in ONE more gossip round so the
  announcement actually leaves the building (the reference's
  ``leave_propagate_delay``), then goes dark.
- **rejoin**: a dead node returns with a bumped incarnation and announces a
  ``K_ALIVE`` fact, refuting any standing suspicion/death facts (the
  reference's restart-on-same-address scenario).

Per-round events are capped at ``max_events`` per kind (the same bounded
injection discipline as the failure detector); sampled candidates beyond
the cap simply don't fire that round, keeping rates honest in expectation.
All randomness is explicit PRNG keys; the churn masks are ordinary traced
tensors, so the whole process jits and scans.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_ALIVE,
    K_LEAVE,
    inject_facts_batch,
    pick_bounded,
)
from serf_tpu.models.swim import ClusterConfig, ClusterState, cluster_round


@dataclasses.dataclass(frozen=True)
class ChurnConfig:
    fail_rate: float = 0.0      # per-alive-node per-round crash probability
    leave_rate: float = 0.0     # per-alive-node per-round graceful-leave prob
    rejoin_rate: float = 0.0    # per-dead-node per-round rejoin probability
    max_events: int = 8         # cap per kind per round (bounded injection)
    #: gossip rounds a graceful leaver stays up AFTER announcing K_LEAVE —
    #: the device analog of the reference's leave broadcast drain
    #: (broadcast_timeout + propagate delay spans several gossip
    #: intervals).  One round is a ~e^-fanout chance the pull exchange
    #: never reads the origin before it goes dark, orphaning the fact.
    leave_linger_rounds: int = 3


def churn_round(state: GossipState, cfg: GossipConfig, ccfg: ChurnConfig,
                key: jax.Array):
    """Sample and apply one round of churn events to the gossip substate.

    Returns ``(state, new_leavers)``: fails and rejoins take effect
    immediately; graceful leavers have announced their ``K_LEAVE`` fact
    but stay alive for ``leave_linger_rounds`` more gossip rounds —
    thread ``new_leavers`` through ``linger_step`` and apply its
    ``go_down`` mask after each round.  Going dark immediately would let
    the dead-sender masking in ``round_step`` silence the announcement
    before it ever leaves the origin.
    """
    n = cfg.n
    k_f, k_l, k_r, k_pf, k_pl, k_pr = jax.random.split(key, 6)

    want_fail = jax.random.bernoulli(k_f, ccfg.fail_rate, (n,)) & state.alive
    want_leave = (jax.random.bernoulli(k_l, ccfg.leave_rate, (n,))
                  & state.alive & ~want_fail)
    want_rejoin = (jax.random.bernoulli(k_r, ccfg.rejoin_rate, (n,))
                   & ~state.alive)

    fails, _, _ = pick_bounded(want_fail, ccfg.max_events, k_pf)
    leaves, leave_subj, leave_act = pick_bounded(
        want_leave, ccfg.max_events, k_pl)
    rejoins, rejoin_subj, rejoin_act = pick_bounded(
        want_rejoin, ccfg.max_events, k_pr)

    # a rejoiner returns with a bumped incarnation so its alive
    # announcement refutes standing suspect/dead facts
    incarnation = jnp.where(rejoins, state.incarnation + 1, state.incarnation)
    alive = (state.alive & ~fails) | rejoins
    state = state._replace(alive=alive, incarnation=incarnation)

    ltime = state.round.astype(jnp.uint32)
    if ccfg.leave_rate > 0:
        state = inject_facts_batch(
            state, cfg, subjects=leave_subj, kind=K_LEAVE,
            incarnations=incarnation[leave_subj],
            ltimes=jnp.full((ccfg.max_events,), ltime),
            origins=leave_subj, active=leave_act)
    if ccfg.rejoin_rate > 0:
        state = inject_facts_batch(
            state, cfg, subjects=rejoin_subj, kind=K_ALIVE,
            incarnations=incarnation[rejoin_subj],
            ltimes=jnp.full((ccfg.max_events,), ltime),
            origins=rejoin_subj, active=rejoin_act)
    return state, leaves


def linger_init(n: int) -> jnp.ndarray:
    """u8[N] leave countdown; 0 = not leaving."""
    return jnp.zeros((n,), jnp.uint8)


def linger_step(countdown: jnp.ndarray, new_leavers: jnp.ndarray,
                linger_rounds: int, alive=None):
    """Advance the leave-linger countdown one gossip round.

    Returns ``(countdown', go_down)``: ``go_down`` marks leavers whose
    drain window just expired — apply ``alive & ~go_down`` after the
    round's exchange.  New leavers (re-)arm at ``linger_rounds`` (clamped
    to the u8 countdown's range — silently wrapping would disarm
    multiples of 256 entirely).  Pass ``alive`` to clear the countdown of
    nodes that died mid-linger: a dead node is not draining, and a stale
    armed countdown would otherwise force it back down the round after a
    rejoin."""
    if alive is not None:
        countdown = jnp.where(alive, countdown, jnp.uint8(0))
    arm = jnp.uint8(max(1, min(255, linger_rounds)))
    cd = jnp.where(new_leavers, arm, countdown)
    armed = cd > 0
    cd = jnp.where(armed, cd - 1, cd)
    go_down = armed & (cd == 0)
    return cd, go_down


class ChurnTrace(NamedTuple):
    """Ground-truth bookkeeping carried through a churned run."""

    ever_down: jnp.ndarray     # bool[N] was non-alive at any point
    always_up: jnp.ndarray     # bool[N] alive through the whole run


def run_cluster_churn(state: ClusterState, cfg: ClusterConfig,
                      ccfg: ChurnConfig, key: jax.Array, num_rounds: int):
    """lax.scan driver: churn + full protocol round, with ground-truth trace.

    Returns ``(final ClusterState, ChurnTrace)`` — the trace is what churn
    assertions need: nodes that were **always up** must never be believed
    dead (no false deaths), nodes down at the end must be detected within
    the suspicion window.
    """
    n = cfg.n
    trace = ChurnTrace(ever_down=~state.gossip.alive,
                       always_up=state.gossip.alive)

    def body(carry, subkey):
        st, tr, cd = carry
        k_churn, k_round = jax.random.split(subkey)
        g, new_leavers = churn_round(st.gossip, cfg.gossip, ccfg, k_churn)
        st = st._replace(gossip=g)
        st = cluster_round(st, cfg, k_round)
        # leavers drain their announcement for linger rounds, then go dark
        cd, go_down = linger_step(cd, new_leavers, ccfg.leave_linger_rounds,
                                  alive=st.gossip.alive)
        g = st.gossip
        st = st._replace(gossip=g._replace(alive=g.alive & ~go_down))
        tr = ChurnTrace(ever_down=tr.ever_down | ~st.gossip.alive,
                        always_up=tr.always_up & st.gossip.alive)
        return (st, tr, cd), ()

    keys = jax.random.split(key, num_rounds)
    (final, trace, _cd), _ = jax.lax.scan(
        body, (state, trace, linger_init(n)), keys)
    return final, trace
