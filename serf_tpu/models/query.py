"""Device-plane query engine: scatter a question, gather acks + responses.

The TPU-native vectorization of the serf query machinery (SURVEY.md §7
stage 7; reference serf-core/src/serf/query.rs:388-601, base.rs:972-1154,
1655-1780):

- **Scatter**: a query is a fact of kind ``K_QUERY`` in the shared gossip
  ring — dissemination to every node is the same transmit-limited gossip
  that carries intents and user events (reference: query_broadcasts queue).
- **Filters**: the reference evaluates Id-list and Tag-regex filters per
  node (query.rs:439-521).  On device a filter is a precomputed eligibility
  mask ``bool[N]`` — ``id_filter_mask`` / ``tag_filter_mask`` build the two
  reference filter kinds from an id list / a tag plane.
- **Ack/response gather**: a node that learns the query, passes the filter,
  and is alive "sends" an ack (if requested) and a response to the origin —
  delivery is direct plus ``relay_factor`` relayed copies through random
  alive intermediates (reference relay_response, query.rs:523-601); a
  message arrives if ANY path survives the drop masks.  Duplicate delivery
  dedups by construction (boolean OR — the reference's per-source dedup
  sets, query.rs:240-369).
- **Timeouts**: a query closes after ``timeout_rounds``; the default is the
  reference's ``mult × ceil(log10(N+1))`` in gossip rounds
  (query.rs:421-427 with the gossip interval factored out).
- **Conflict resolution**: ``majority_vote`` is the segment-sum form of
  ``resolve_node_conflict`` (base.rs:1655-1780): bincount responder votes,
  winner must hold a strict majority of responses.

Fault injection (per-path drop masks) is an input tensor, like everywhere
else on the device plane.
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_QUERY,
    inject_fact,
    rolled_rows,
    sample_offsets,
    unpack_bits,
)


@dataclasses.dataclass(frozen=True)
class QueryConfig:
    """Static query-engine shapes + protocol constants."""

    q_slots: int = 8           # concurrent in-flight query capacity (ring)
    relay_factor: int = 0      # relayed response copies (reference ≤5)
    timeout_mult: int = 16     # reference query_timeout_mult

    def __post_init__(self):
        if not (0 <= self.relay_factor <= 5):
            raise ValueError("relay_factor must be in [0, 5] (reference cap)")


def default_timeout_rounds(n: int, timeout_mult: int = 16) -> int:
    """Query deadline in gossip rounds: mult × ceil(log10(N+1))."""
    return timeout_mult * max(1, math.ceil(math.log10(n + 1)))


class QueryState(NamedTuple):
    """Q in-flight queries over an N-node cluster, struct-of-arrays."""

    origin: jnp.ndarray      # i32[Q] originating node
    fact_slot: jnp.ndarray   # i32[Q] gossip-ring slot carrying the query
    ltime: jnp.ndarray       # u32[Q] query lamport time
    deadline: jnp.ndarray    # i32[Q] round after which the query is closed
    want_ack: jnp.ndarray    # bool[Q]
    eligible: jnp.ndarray    # bool[Q, N] filter mask (id/tag filters applied)
    valid: jnp.ndarray       # bool[Q]
    attempted: jnp.ndarray   # bool[Q, N] node sent its ack/response
    acked: jnp.ndarray       # bool[Q, N] origin received node's ack
    responded: jnp.ndarray   # bool[Q, N] origin received node's response
    resp_value: jnp.ndarray  # i32[Q, N] response payload seen at origin
    next_q: jnp.ndarray      # i32 scalar ring cursor


def make_queries(cfg: GossipConfig, qcfg: QueryConfig) -> QueryState:
    q, n = qcfg.q_slots, cfg.n
    return QueryState(
        origin=jnp.zeros((q,), jnp.int32),
        fact_slot=jnp.zeros((q,), jnp.int32),
        ltime=jnp.zeros((q,), jnp.uint32),
        deadline=jnp.zeros((q,), jnp.int32),
        want_ack=jnp.zeros((q,), bool),
        eligible=jnp.zeros((q, n), bool),
        valid=jnp.zeros((q,), bool),
        attempted=jnp.zeros((q, n), bool),
        acked=jnp.zeros((q, n), bool),
        responded=jnp.zeros((q, n), bool),
        resp_value=jnp.zeros((q, n), jnp.int32),
        next_q=jnp.asarray(0, jnp.int32),
    )


# -- filters -----------------------------------------------------------------

def id_filter_mask(n: int, ids) -> jnp.ndarray:
    """Reference Filter::Id — only the listed node ids may respond."""
    mask = jnp.zeros((n,), bool)
    ids = jnp.asarray(ids, jnp.int32)
    return mask.at[ids].set(True, mode="drop")


def tag_filter_mask(tag_plane: jnp.ndarray, tag_idx: int,
                    value) -> jnp.ndarray:
    """Reference Filter::Tag — nodes whose tag ``tag_idx`` equals ``value``.

    ``tag_plane`` is the device tag representation: i32[N, T] of interned
    tag values (the host's string regex filter compiles to a value set; an
    equality mask is its device form — regex alternation = OR of masks).
    """
    return tag_plane[:, tag_idx] == jnp.asarray(value, tag_plane.dtype)


def no_filter_mask(n: int) -> jnp.ndarray:
    return jnp.ones((n,), bool)


# -- lifecycle ---------------------------------------------------------------

def launch_query(gossip: GossipState, qstate: QueryState, cfg: GossipConfig,
                 qcfg: QueryConfig, origin, eligible: jnp.ndarray,
                 want_ack=True, timeout_rounds: Optional[int] = None,
                 ltime=None):
    """Open a query: claim the next query slot, scatter a K_QUERY fact.

    Returns ``(gossip', qstate', q_idx)``.  Reusing a ring slot closes the
    old query that lived there (bounded concurrency — the device analog of
    the reference's query dedup ring ``query_buffer_size``).
    """
    if timeout_rounds is None:
        timeout_rounds = default_timeout_rounds(cfg.n, qcfg.timeout_mult)
    qi = qstate.next_q % qcfg.q_slots
    slot = gossip.next_slot % cfg.k_facts
    lt = (gossip.round.astype(jnp.uint32) if ltime is None
          else jnp.asarray(ltime, jnp.uint32))
    gossip = inject_fact(gossip, cfg, subject=qi, kind=K_QUERY,
                         incarnation=0, ltime=lt, origin=origin)
    n = cfg.n
    qstate = QueryState(
        origin=qstate.origin.at[qi].set(jnp.asarray(origin, jnp.int32)),
        fact_slot=qstate.fact_slot.at[qi].set(slot.astype(jnp.int32)
                                              if hasattr(slot, "astype")
                                              else jnp.int32(slot)),
        ltime=qstate.ltime.at[qi].set(lt),
        deadline=qstate.deadline.at[qi].set(
            gossip.round + jnp.int32(timeout_rounds)),
        want_ack=qstate.want_ack.at[qi].set(jnp.asarray(want_ack, bool)),
        eligible=qstate.eligible.at[qi].set(eligible),
        valid=qstate.valid.at[qi].set(True),
        attempted=qstate.attempted.at[qi].set(jnp.zeros((n,), bool)),
        acked=qstate.acked.at[qi].set(jnp.zeros((n,), bool)),
        responded=qstate.responded.at[qi].set(jnp.zeros((n,), bool)),
        resp_value=qstate.resp_value.at[qi].set(jnp.zeros((n,), jnp.int32)),
        next_q=qstate.next_q + 1,
    )
    return gossip, qstate, qi


def query_round(gossip: GossipState, qstate: QueryState, cfg: GossipConfig,
                qcfg: QueryConfig, key: jax.Array,
                response_value: Optional[jnp.ndarray] = None,
                drop_direct: Optional[jnp.ndarray] = None,
                drop_relay: Optional[jnp.ndarray] = None) -> QueryState:
    """One gather step: new knowers of each open query send ack + response.

    - ``response_value``: i32[N] per-node answer payload (the app handler's
      return, vectorized).  Defaults to the node index.
    - ``drop_direct``: bool[Q, N] — the responder→origin direct send is lost.
    - ``drop_relay``: bool[Q, N, R] — relayed copy r is lost in transit.

    A responder attempts exactly once (first round it knows + passes the
    filter, reference base.rs:1002-1042's (ltime,id) dedup); a lost attempt
    is lost for good, but any surviving relay path delivers.  Arrivals OR
    into ``acked``/``responded`` — duplicate relay deliveries are absorbed,
    matching the reference's per-source dedup sets.
    """
    q, n = qcfg.q_slots, cfg.n
    if response_value is None:
        response_value = jnp.arange(n, dtype=jnp.int32)

    known = unpack_bits(gossip.known, cfg.k_facts)            # bool[N, K]
    knows = known[:, qstate.fact_slot].T                      # bool[Q, N]
    # the ring slot must still carry OUR query fact (not overwritten)
    slot_is_ours = (gossip.facts.kind[qstate.fact_slot] == K_QUERY) \
        & (gossip.facts.subject[qstate.fact_slot] == jnp.arange(q)) \
        & gossip.facts.valid[qstate.fact_slot]                # bool[Q]
    open_q = qstate.valid & slot_is_ours & (gossip.round <= qstate.deadline)

    senders = (knows & qstate.eligible & gossip.alive[None, :]
               & open_q[:, None] & ~qstate.attempted)         # bool[Q, N]

    # delivery: direct path + relay_factor independent relayed copies
    arrive = jnp.ones((q, n), bool) if drop_direct is None else ~drop_direct
    origin_alive = gossip.alive[qstate.origin]                # bool[Q]
    if qcfg.relay_factor > 0:
        r = qcfg.relay_factor
        if cfg.peer_sampling == "rotation":
            # one random rotation per (query, relay path): relay liveness
            # is a contiguous roll, no Q×N×R random gather (serial-loop
            # cost on TPU; see GossipConfig.peer_sampling)
            offs = sample_offsets(key, q * r, n).reshape(q, r)
            rows = []
            for qi in range(q):
                any_ok = jnp.zeros((n,), bool)
                for ri in range(r):
                    ok = rolled_rows(gossip.alive, offs[qi, ri])
                    if drop_relay is not None:
                        ok = ok & ~drop_relay[qi, :, ri]
                    any_ok = any_ok | ok
                rows.append(any_ok)
            arrive = arrive | jnp.stack(rows)
        else:
            mids = jax.random.randint(key, (q, n, r), 0, n)   # i32[Q, N, R]
            relay_ok = gossip.alive[mids]                     # bool[Q, N, R]
            if drop_relay is not None:
                relay_ok = relay_ok & ~drop_relay
            arrive = arrive | jnp.any(relay_ok, axis=-1)
    arrive = arrive & origin_alive[:, None]

    delivered = senders & arrive
    acked = qstate.acked | (delivered & qstate.want_ack[:, None])
    responded = qstate.responded | delivered
    resp_value = jnp.where(delivered, response_value[None, :],
                           qstate.resp_value)
    return qstate._replace(attempted=qstate.attempted | senders,
                           acked=acked, responded=responded,
                           resp_value=resp_value)


# -- views -------------------------------------------------------------------

def num_acks(qstate: QueryState) -> jnp.ndarray:
    """i32[Q] acks received per query (reference serf.query.acks metric)."""
    return jnp.sum(qstate.acked, axis=1).astype(jnp.int32)


def num_responses(qstate: QueryState) -> jnp.ndarray:
    return jnp.sum(qstate.responded, axis=1).astype(jnp.int32)


def responders(qstate: QueryState, qi) -> jnp.ndarray:
    """bool[N]: nodes whose response reached the origin for query ``qi``."""
    return qstate.responded[qi]


# -- conflict resolution -----------------------------------------------------

def majority_vote(votes: jnp.ndarray, responded: jnp.ndarray,
                  num_candidates: int):
    """Conflict-resolution majority vote as a segment-sum
    (reference base.rs:1655-1780, internal_query handle_conflict).

    ``votes``: i32[N] — each node's belief (e.g. interned address of the
    conflicted id); ``responded``: bool[N] — whose response arrived.
    Returns ``(winner, winner_count, total_responses)``; the winner stands
    only if ``winner_count >= total//2 + 1`` (strict majority), exactly the
    host engine's ``_resolve_node_conflict`` arithmetic.
    """
    weights = responded.astype(jnp.int32)
    counts = jnp.zeros((num_candidates,), jnp.int32).at[votes].add(
        weights, mode="drop")
    winner = jnp.argmax(counts).astype(jnp.int32)
    total = jnp.sum(weights)
    return winner, counts[winner], total


def majority_holds(winner_count, total) -> jnp.ndarray:
    """Strict majority test: count >= total//2 + 1 (host serf.py parity)."""
    return (total > 0) & (winner_count >= total // 2 + 1)
