"""Device-plane serf membership: Lamport-ordered join/leave intent views.

The serf layer on top of SWIM: member status is decided by the
highest-Lamport-time intent each node knows (reference handlers
``handle_node_join_intent`` / ``handle_node_leave_intent``,
serf-core/src/serf/base.rs:1338-1572).  On the device plane intents are
facts (kind K_JOIN / K_LEAVE with an ltime); a node's view of a subject is a
pure function of the facts it knows — the batched merge semilattice of
SURVEY.md §7 ("hard parts"): max-ltime wins, strictly-greater to supersede,
so round-batched application reaches the same fixpoint as the reference's
serialized application for any intent set with distinct ltimes.  (At equal
ltimes the reference is arrival-order dependent; the device rule breaks ties
toward LEAVE, the conservative choice.)

Status lattice (mirrors ``serf_tpu.types.member.MemberStatus``):
NONE(0) / ALIVE(1) / LEAVING(2).  FAILED/LEFT come from composing with the
SWIM plane (``serf_tpu.models.failure``): a swim-dead subject whose freshest
intent is LEAVE resolves LEFT, otherwise FAILED — the same
Leaving->Left / Alive->Failed split as reference base.rs:1375-1440.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_JOIN,
    K_LEAVE,
    ltime_rel,
    unpack_bits,
)

# resolved view statuses
V_NONE = 0
V_ALIVE = 1
V_LEAVING = 2
V_LEFT = 3
V_FAILED = 4


def intent_views(state: GossipState, cfg: GossipConfig,
                 subjects: jnp.ndarray) -> jnp.ndarray:
    """u8[N, S]: each node's serf-status view of each subject in
    ``subjects`` (i32[S]), from the join/leave intent facts it knows.

    Per (knower, subject): the known intent with the highest ltime wins;
    ties prefer LEAVE.  No known intent -> NONE.
    """
    n, k = cfg.n, cfg.k_facts
    known = unpack_bits(state.known, k)                       # bool[N, K]
    facts = state.facts
    is_join = (facts.kind == K_JOIN) & facts.valid
    is_leave = (facts.kind == K_LEAVE) & facts.valid
    # [S, K] fact-about-subject masks
    about = facts.subject[None, :] == subjects[:, None]
    # wrap-safe supersession (ltime is u32 and a long-lived cluster's
    # clock wraps): compare in the windowed two's-complement embedding —
    # signed offsets relative to any intent fact's ltime preserve order
    # while the live ltimes span < 2^31 (``ltime_window_violation`` is
    # the fail-loud guard for when they don't).  A plain u32 max would
    # make a pre-wrap intent (huge) supersede a post-wrap one (small)
    # forever.
    pivot = facts.ltime[jnp.argmax(is_join | is_leave)]
    rel = ltime_rel(facts.ltime, pivot)                       # i32[K]
    sentinel = jnp.iinfo(jnp.int32).min

    def per_knower(known_row):
        # known_row: bool[K]
        jmask = known_row[None, :] & about & is_join[None, :]     # [S, K]
        lmask = known_row[None, :] & about & is_leave[None, :]
        jany = jnp.any(jmask, axis=1)
        lany = jnp.any(lmask, axis=1)
        jbest = jnp.max(jnp.where(jmask, rel[None, :], sentinel), axis=1)
        lbest = jnp.max(jnp.where(lmask, rel[None, :], sentinel), axis=1)
        # highest ltime wins; ties (and join-vs-leave at equal rel)
        # prefer LEAVE — the conservative choice (module docstring)
        status = jnp.where(
            ~jany & ~lany, V_NONE,
            jnp.where(jany & (~lany | (jbest > lbest)),
                      V_ALIVE, V_LEAVING))
        return status.astype(jnp.uint8)

    return jax.vmap(per_knower)(known)                        # u8[N, S]


def composed_views(state: GossipState, cfg: GossipConfig,
                   subjects: jnp.ndarray,
                   swim_dead: jnp.ndarray) -> jnp.ndarray:
    """Compose intent views with the SWIM plane: ``swim_dead`` (bool[N, S] —
    knower i believes subject j dead) refines ALIVE->FAILED and
    LEAVING->LEFT; NONE stays NONE (a death notice about a member we never
    saw join carries no serf status — reference base.rs:1375-1440 only
    transitions known members)."""
    views = intent_views(state, cfg, subjects)
    return jnp.where(
        swim_dead & (views != V_NONE),
        jnp.where(views == V_LEAVING, jnp.uint8(V_LEFT), jnp.uint8(V_FAILED)),
        views)


def converged(state: GossipState, cfg: GossipConfig,
              subjects: jnp.ndarray) -> jnp.ndarray:
    """bool: all alive knowers agree on every subject's view."""
    views = intent_views(state, cfg, subjects)
    alive = state.alive
    # compare every row to the first alive row
    first = jnp.argmax(alive)
    ref = views[first]
    agree = jnp.all(views == ref[None, :], axis=1) | ~alive
    return jnp.all(agree)
