"""Device-plane gossip dissemination: the cluster as arrays in HBM.

This is the TPU-native re-design of serf's dissemination machinery
(SURVEY.md §7, stage 3/4).  The mapping from the reference:

- serf's broadcast queues + ring dedup buffers (serf-core/src/broadcast.rs,
  base.rs:750-837) become a bounded **fact table**: K slots of immutable
  facts ``(subject, kind, incarnation, ltime)``.  New facts overwrite ring
  slots, exactly like the reference's ``buffer[ltime % len]`` dedup cells.
- each simulated node's state is a row: a packed bitset of which facts it
  knows (``known``: N×W uint32) and a **learn-round stamp** (``stamp``:
  N×K uint8 — the round mod 256 at which the fact became known, valid only
  where the known bit is set).  A fact's knowledge age and its remaining
  transmit budget (the TransmitLimitedQueue, vectorized) are DERIVED:
  ``age = (round - stamp) mod 256`` (``age_of``) and ``budget =
  max(0, transmit_limit - age)`` (``budgets_of``).  Stamps are written
  once per LEARN event, never ticked — so neither the per-round budget
  decrement nor fact retirement rewrites the N×K plane (see
  ``GossipState``).
- a gossip round = sample ``fanout`` peers per node, gather their packed
  packet words, bitwise-OR, then a masked Lamport-style merge — pure
  elementwise math plus one gather, which is exactly what the MXU-era memory
  system wants.  No scatter: the round uses *pull* sampling (each node
  pulls from ``fanout`` random peers), which converges like push-gossip and
  keeps the kernel gather-only; transmit budgets still decrement once per
  round per selected fact, matching the reference's drain-once-per-tick
  semantics (memberlist gossip).
- packet-byte budgets degenerate to the fact-table bound K (a fact slot is
  O(16B), K·16B < the reference's 1400B UDP budget for K ≤ 64).

Everything here is jit-compatible with static shapes; dynamic membership is
a liveness mask (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

# fact kinds (precedence for view resolution: higher wins at equal
# incarnation; alive refutes suspect at *higher* incarnation only)
K_NONE = 0
K_JOIN = 1        # serf join intent (ltime-ordered)
K_LEAVE = 2       # serf leave intent (ltime-ordered)
K_ALIVE = 3       # swim alive (incarnation-ordered; refutes suspect/dead)
K_SUSPECT = 4     # swim suspicion (starts a timer at each knower)
K_DEAD = 5        # swim death declaration
K_USER_EVENT = 6  # user event broadcast (subject = event id)
K_QUERY = 7       # query scatter (subject = query slot id; models/query.py)


class FactTable(NamedTuple):
    """K immutable dissemination facts (the global 'what is being gossiped')."""

    subject: jnp.ndarray       # i32[K] node id or event id
    kind: jnp.ndarray          # u8[K]
    incarnation: jnp.ndarray   # u32[K]
    ltime: jnp.ndarray         # u32[K]
    valid: jnp.ndarray         # bool[K]


class GossipState(NamedTuple):
    """The whole simulated cluster, struct-of-arrays.

    There is deliberately no transmit-budget plane and no stored age plane:
    a fact's knowledge age is fully determined by its learn-round stamp —
    ``age = (round - stamp) mod 256`` where the known bit is set (garbage
    where it isn't) — and its remaining transmit budget by that age:
    ``budget = max(0, transmit_limit - age)`` (learn: budget=limit, age=0;
    each round: one transmit as long as age < limit).  Deriving both
    (``age_of``/``budgets_of``) means the u8[N, K] plane is written only
    on LEARN events (one full-plane select in the round's merge) — the
    round-1 stored-budget plane's decrement pass AND the stored-age
    plane's saturating tick AND the per-injection full-plane retirement
    rewrite (64 MB × 3-4 injections/round at 1M) are all gone; retirement
    is just the known-bit clear.

    The mod-256 stamp wraps; ``round_step`` re-pins stale stamps to
    ``AGE_PIN`` every ``CLAMP_EVERY`` rounds (an amortized full-plane
    pass) so a fact's derived age can never wrap back under
    ``transmit_limit``/``suspicion_rounds`` — both of which config
    validation bounds to ``AGE_PIN``.

    One semantic consequence, closer to the reference than the stored
    budget plane was: a node that is down ages past its budgets, so a
    rejoiner does not resume retransmitting stale facts (the reference's
    restarted node comes back with an empty broadcast queue,
    serf-core/src/serf/base.rs:62-344 — queues are rebuilt, not restored).
    """

    facts: FactTable
    known: jnp.ndarray          # u32[N, W]  packed known-fact bitset
    stamp: jnp.ndarray          # u8[N, K]   round mod 256 when learned
                                #            (valid only where known)
    alive: jnp.ndarray          # bool[N]    ground-truth liveness
    incarnation: jnp.ndarray    # u32[N]     ground-truth own incarnation
    round: jnp.ndarray          # i32 scalar
    next_slot: jnp.ndarray      # i32 scalar ring cursor for fact injection
    last_learn: jnp.ndarray     # i32 scalar round of the most recent learn
                                # event ANYWHERE (inject or merge).  Once
                                # `round - last_learn >= transmit_limit`,
                                # every knower's derived age is >= the
                                # limit, so NO fact is sendable and the
                                # gossip exchange is provably an identity
                                # — round_step skips it under lax.cond
                                # (serf's empty broadcast queue sends
                                # nothing).  Every path that writes
                                # stamps/known must update this scalar.
    tombstone: jnp.ndarray      # bool[N]    durable per-subject death
                                # record: set when a fully-disseminated
                                # K_DEAD fact RETIRES from the ring
                                # (slot overwritten), cleared by any
                                # K_ALIVE injection for the subject
                                # (refutation / rejoin).  The device
                                # analog of the reference's member table
                                # holding FAILED after the broadcast
                                # queue drains (base.rs:1375-1440): ring
                                # facts are transient dissemination
                                # state, but the cluster must not FORGET
                                # a death when the slot recycles — under
                                # sustained load the ring cycles every
                                # k_facts/rate rounds.  A death that
                                # retires only PARTIALLY disseminated is
                                # dropped (documented compression: a
                                # per-subject bit cannot represent
                                # per-knower splits once the per-knower
                                # evidence is gone; the detector will
                                # re-suspect such a subject).
    sendable: jnp.ndarray       # u32[N, W]  packed CACHE of the selection
                                # predicate `known & (mod_age < limit)`
                                # (alive NOT folded in — liveness changes
                                # externally).  Valid ONLY when
                                # sendable_round == round; see below.
    sendable_round: jnp.ndarray  # i32 scalar: the round `sendable` is
                                # valid for (-1 = never).  INVARIANT:
                                # sendable_round == R implies sendable ==
                                # pack(known & (mod_age(R) < limit)).
                                # Writers: the merge's learn pass
                                # recomputes the full plane for round+1
                                # (the only place the validity round
                                # advances — expiry transitions are only
                                # visible while the stamp plane is being
                                # streamed anyway); inject/push_pull OR
                                # their age-0 learn bits in and clear
                                # retired slots, which preserves validity
                                # for the SAME round (and is harmless on
                                # a stale plane — a stale plane is never
                                # read).  Selection uses the cache only
                                # when valid, else falls back to the
                                # stamp-plane recompute (accounting.py
                                # quantifies the 64 MB/round this saves
                                # in the sustained regime at 1M).


@dataclasses.dataclass(frozen=True)
class GossipConfig:
    """Static configuration (shapes + protocol constants)."""

    n: int                      # number of simulated nodes
    k_facts: int = 64           # fact-table capacity (ring)
    fanout: int = 3             # gossip_nodes
    retransmit_mult: int = 4    # transmit budget = mult * ceil(log10(n+1))
    use_pallas: bool = False    # fused Pallas kernels for phases 1+3
    #: "iid": every node samples uniform peers each round — the direct
    #: analog of memberlist's random gossip targets, but each sample is a
    #: random-index gather/scatter, which XLA lowers to a SERIAL loop on
    #: TPU (~10 ms per 1M-row op — measured; the whole round budget is
    #: <1 ms).  "rotation": each round draws ``fanout`` random rotation
    #: offsets shared by all nodes; node i's f-th peer is (i+off_f) mod n,
    #: so every peer read is one contiguous dynamic-slice (``rolled_rows``)
    #: and every inverse ("who contacted me") is analytic.  A fresh random
    #: cyclic matching per round is the vectorized analog of memberlist's
    #: shuffled round-robin probe list and converges like random gossip
    #: (random Cayley-graph expanders); it is the intended mode at scale.
    peer_sampling: str = "iid"
    #: use the packed ``sendable`` cache for packet selection when valid
    #: (GossipState.sendable_round): saves the selection's full stamp-
    #: plane read (64 MB/round at 1M) whenever the previous round's merge
    #: learned anything — i.e. nearly always under sustained load.
    #: Bit-exact either way (tests/test_sendable_cache.py pins it);
    #: the flag exists for that A/B and as an escape hatch.
    use_sendable_cache: bool = True

    def __post_init__(self):
        if self.peer_sampling not in ("iid", "rotation"):
            raise ValueError(
                f"unknown peer_sampling {self.peer_sampling!r}")
        if self.transmit_limit > AGE_PIN:
            # derived ages are pinned at AGE_PIN by the periodic stamp
            # clamp; a limit above the pin would let pinned (very old)
            # facts re-enter the sending set
            raise ValueError(
                f"transmit_limit {self.transmit_limit} exceeds the stamp "
                f"age pin {AGE_PIN} (lower retransmit_mult)")

    @property
    def words(self) -> int:
        assert self.k_facts % 32 == 0, "k_facts must be a multiple of 32"
        return self.k_facts // 32

    @property
    def transmit_limit(self) -> int:
        import math
        return self.retransmit_mult * max(1, math.ceil(math.log10(self.n + 1)))


#: derived ages are pinned here by the periodic stamp clamp; must exceed
#: every age threshold the protocol compares against (transmit_limit,
#: suspicion_rounds — both config-validated against it)
AGE_PIN = 200
#: rounds between stamp-clamp passes.  Correctness bound: a known fact's
#: derived age is ≤ AGE_PIN right after a clamp, so it reaches at most
#: AGE_PIN + CLAMP_EVERY < 256 before the next one — it can never wrap
#: back under the thresholds.  Cost: one full-plane pass per CLAMP_EVERY
#: rounds (amortized ~2 MB/round at 1M×64).
CLAMP_EVERY = 32


def make_state(cfg: GossipConfig) -> GossipState:
    n, k, w = cfg.n, cfg.k_facts, cfg.words
    facts = FactTable(
        subject=jnp.full((k,), -1, jnp.int32),
        kind=jnp.zeros((k,), jnp.uint8),
        incarnation=jnp.zeros((k,), jnp.uint32),
        ltime=jnp.zeros((k,), jnp.uint32),
        valid=jnp.zeros((k,), bool),
    )
    return GossipState(
        facts=facts,
        known=jnp.zeros((n, w), jnp.uint32),
        stamp=jnp.zeros((n, k), jnp.uint8),
        alive=jnp.ones((n,), bool),
        incarnation=jnp.ones((n,), jnp.uint32),
        round=jnp.asarray(0, jnp.int32),
        next_slot=jnp.asarray(0, jnp.int32),
        last_learn=jnp.asarray(0, jnp.int32),
        tombstone=jnp.zeros((n,), bool),
        sendable=jnp.zeros((n, w), jnp.uint32),
        sendable_round=jnp.asarray(-1, jnp.int32),
    )


def round_u8(round_) -> jnp.ndarray:
    """The stamp-plane representation of a round counter: its low byte."""
    return (jnp.asarray(round_, jnp.int32) & 0xFF).astype(jnp.uint8)


def mod_age(state: GossipState, round_=None) -> jnp.ndarray:
    """u8[N, K]: rounds since learned via wrapping u8 subtraction.
    VALID ONLY where the known bit is set — callers must gate on the
    ``known`` bitset (every protocol predicate already does)."""
    r = state.round if round_ is None else round_
    return round_u8(r) - state.stamp


def age_of(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """u8[N, K]: knowledge age with the round-1 stored-plane convention
    (255 = never/unknown) — the gated, allocation-honest view for metrics
    and tests; the round kernels use ``mod_age`` + known-gating inline."""
    known = unpack_bits(state.known, cfg.k_facts)
    return jnp.where(known, mod_age(state), jnp.uint8(255))


def budgets_of(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """u8[N, K]: remaining transmit budget, derived from knowledge age
    (see the GossipState docstring for the invariant)."""
    limit = jnp.uint8(cfg.transmit_limit)
    age = age_of(state, cfg)
    return jnp.where(age < limit, limit - age, jnp.uint8(0))


def sending_mask(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """bool[N, K]: facts with remaining transmit budget at alive nodes —
    the per-round packet-selection predicate.  THE place the budget
    derivation is encoded for the round kernels (round_step,
    push_round_step, ring.round_step_ring); keep in sync with
    ``budgets_of``."""
    known = unpack_bits(state.known, cfg.k_facts)
    return (known & (mod_age(state) < jnp.uint8(cfg.transmit_limit))
            & state.alive[:, None])


def bump_last_learn(learned_any, learn_round, prev) -> jnp.ndarray:
    """i32 scalar: ``learn_round`` if ``learned_any`` else ``prev``.

    THE one way to maintain GossipState.last_learn — every path that
    writes known/stamp must route through this (the quiet-round gate's
    correctness depends on it; a writer that forgets the bump freezes
    dissemination of its facts once the gate closes)."""
    return jnp.where(learned_any, jnp.asarray(learn_round, jnp.int32), prev)


def clamp_stamps(known: jnp.ndarray, stamp: jnp.ndarray, round_,
                 k_facts: int) -> jnp.ndarray:
    """Re-pin stale stamps so derived ages can never wrap (see AGE_PIN/
    CLAMP_EVERY).  Rides a lax.cond in the round kernels: the full-plane
    pass runs once per CLAMP_EVERY rounds."""
    def clamp(s):
        kb = unpack_bits(known, k_facts)
        r8 = round_u8(round_)
        stale = kb & ((r8 - s) > jnp.uint8(AGE_PIN))
        return jnp.where(stale, r8 - jnp.uint8(AGE_PIN), s)

    return jax.lax.cond(
        jnp.asarray(round_, jnp.int32) % CLAMP_EVERY == 0,
        clamp, lambda s: s, stamp)


# -- rotation addressing -----------------------------------------------------

def rolled_rows(x: jnp.ndarray, shift, doubled=None) -> jnp.ndarray:
    """``y[i] = x[(i + shift) % n]`` along axis 0, without a gather.

    A random-index gather over 1M small rows lowers to a serial loop on
    TPU (measured ~10 ms each); this is one concatenate + one contiguous
    dynamic slice (~2 sequential passes).  ``shift`` may be a traced
    scalar in [0, n).

    ``doubled``: optionally the precomputed ``concatenate([x, x])`` —
    pass it when slicing the SAME array at several shifts (the fanout
    exchange, the indirect-probe rolls) so the doubling materializes
    once by construction rather than by trusting XLA CSE to dedupe
    identical concatenates."""
    n = x.shape[0]
    if doubled is None:
        doubled = jnp.concatenate([x, x], axis=0)
    return jax.lax.dynamic_slice_in_dim(
        doubled, jnp.asarray(shift, jnp.int32), n, axis=0)


def sample_offsets(key: jax.Array, m: int, n: int) -> jnp.ndarray:
    """``m`` random nonzero rotation offsets in [1, n)."""
    return jax.random.randint(key, (m,), 1, n, dtype=jnp.int32)


# -- bit packing helpers -----------------------------------------------------

def _bit_weights() -> jnp.ndarray:
    return (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))


def pack_bits(mask: jnp.ndarray) -> jnp.ndarray:
    """bool[..., K] -> u32[..., K/32]"""
    *lead, k = mask.shape
    m = mask.reshape(*lead, k // 32, 32).astype(jnp.uint32)
    return jnp.sum(m * _bit_weights(), axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray, k: int) -> jnp.ndarray:
    """u32[..., W] -> bool[..., K]"""
    bits = (words[..., :, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    *lead, w, _ = bits.shape
    return bits.reshape(*lead, k).astype(bool)


# -- fact injection ----------------------------------------------------------

def inject_fact(state: GossipState, cfg: GossipConfig, subject, kind,
                incarnation, ltime, origin) -> GossipState:
    """Place one fact into the next ring slot; ``origin`` knows it first.

    Overwriting an old slot retires that fact everywhere (the ring is the
    same bounded-buffer semantics as the reference's dedup cells).  Traceable
    under jit (origin/subject/... may be traced scalars).
    """
    slot = state.next_slot % cfg.k_facts
    word, bit = slot // 32, slot % 32

    # durable death record (see GossipState.tombstone): a K_DEAD fact
    # being retired by this overwrite folds into the tombstone IF its
    # dissemination completed (every alive node knows it); the injected
    # fact clears the record when it is a superseding K_ALIVE
    old_kind = state.facts.kind[slot]
    old_subject = jnp.clip(state.facts.subject[slot], 0)
    known_col = ((state.known[:, word]
                  >> jnp.asarray(bit, jnp.uint32)) & 1).astype(bool)
    covered = jnp.all(known_col | ~state.alive) & jnp.any(state.alive)
    # supersession check (as accusations_pending): a REFUTED death — the
    # subject bumped its incarnation above the declaration's — must not
    # fold, or a live node would be durably recorded dead with no
    # clearing path
    not_superseded = (state.facts.incarnation[slot]
                      >= state.incarnation[old_subject])
    dead_retired = (state.facts.valid[slot] & (old_kind == K_DEAD)
                    & covered & not_superseded)
    tombstone = state.tombstone.at[old_subject].max(dead_retired)
    is_alive_fact = jnp.asarray(kind, jnp.uint8) == K_ALIVE
    subj_idx = jnp.clip(jnp.asarray(subject, jnp.int32), 0)
    tombstone = tombstone.at[subj_idx].set(
        tombstone[subj_idx] & ~is_alive_fact)

    facts = FactTable(
        subject=state.facts.subject.at[slot].set(jnp.asarray(subject, jnp.int32)),
        kind=state.facts.kind.at[slot].set(jnp.asarray(kind, jnp.uint8)),
        incarnation=state.facts.incarnation.at[slot].set(jnp.asarray(incarnation, jnp.uint32)),
        ltime=state.facts.ltime.at[slot].set(jnp.asarray(ltime, jnp.uint32)),
        valid=state.facts.valid.at[slot].set(True),
    )
    bitmask = (jnp.uint32(1) << bit.astype(jnp.uint32)
               if hasattr(bit, "astype") else jnp.uint32(1 << int(bit)))
    # clear the slot's bit everywhere (fact replaced — the known bit IS the
    # retirement; stale stamps under a cleared bit are never read), then
    # set at origin with a fresh stamp
    known = state.known.at[:, word].set(state.known[:, word] & ~bitmask)
    known = known.at[origin, word].set(known[origin, word] | bitmask)
    stamp = state.stamp.at[origin, slot].set(round_u8(state.round))
    # mirror on the sendable cache (flag-gated at trace time — the
    # escape-hatch config must not pay maintenance): the fresh fact is
    # age-0 sendable at the origin, the retired slot is sendable nowhere
    # — preserves the cache invariant for whatever round the cache is
    # valid for (and is harmless on a stale plane, which is never read)
    sendable = state.sendable
    sendable_round = state.sendable_round
    if cfg.use_sendable_cache:
        sendable = sendable.at[:, word].set(sendable[:, word] & ~bitmask)
        sendable = sendable.at[origin, word].set(
            sendable[origin, word] | bitmask)
    else:
        # learned without mirroring: a later flag-on run must not trust
        # this plane (mixed-flag hygiene)
        sendable_round = jnp.asarray(-1, jnp.int32)
    return state._replace(facts=facts, known=known,
                          stamp=stamp, next_slot=state.next_slot + 1,
                          tombstone=tombstone,
                          sendable=sendable, sendable_round=sendable_round,
                          last_learn=bump_last_learn(True, state.round,
                                                     state.last_learn))


def inject_facts_batch(state: GossipState, cfg: GossipConfig, subjects,
                       kind: int, incarnations, ltimes, origins,
                       active) -> GossipState:
    """Inject up to ``M = len(subjects)`` facts in ONE pass.

    ``active`` (bool[M]) must be a *prefix* mask (all True entries first) —
    active facts take consecutive ring slots starting at ``next_slot``.
    Inactive entries are dropped via out-of-bounds scatter indices.

    Equivalent to ``M`` sequential ``inject_fact`` calls.  With the stamp
    plane the whole batch is two bounded scatters (known-bit set at
    origins, stamp at origins) plus one pass over the N×W word plane for
    retirement — the N×K plane is NOT rewritten (the round-1 sequential
    form moved ~130 MB × M per phase through HBM; the round-2 batched form
    still rewrote the 64 MB age plane once per phase for retirement).
    """
    n, k = cfg.n, cfg.k_facts
    m = subjects.shape[0]
    if m > k:
        # consecutive slots would alias modulo the ring and the scatter-add
        # OR trick would corrupt the known bitmap
        raise ValueError(f"batch of {m} facts exceeds ring capacity {k}")
    subjects = jnp.asarray(subjects, jnp.int32)
    origins = jnp.asarray(origins, jnp.int32)

    slots = (state.next_slot + jnp.arange(m, dtype=jnp.int32)) % k
    # OOB index (k / n) + mode='drop' skips the write entirely
    wslots = jnp.where(active, slots, k)
    worigins = jnp.where(active, origins, n)

    # durable death record (see GossipState.tombstone): retiring,
    # fully-disseminated K_DEAD facts fold in; K_ALIVE injections clear
    # their subjects.  Per retired slot, "covered" = every alive node
    # holds the known bit (m columns of the packed plane).
    r_slots = jnp.clip(slots, 0, k - 1)
    r_words, r_bits = r_slots // 32, (r_slots % 32).astype(jnp.uint32)
    cols = ((state.known[:, r_words] >> r_bits[None, :]) & 1).astype(bool)
    covered = (jnp.all(cols | ~state.alive[:, None], axis=0)
               & jnp.any(state.alive))                        # bool[M]
    r_subj = jnp.clip(state.facts.subject[r_slots], 0)
    # supersession check (see inject_fact): refuted deaths must not fold
    not_superseded = (state.facts.incarnation[r_slots]
                      >= state.incarnation[r_subj])
    dead_retired = (state.facts.valid[r_slots]
                    & (state.facts.kind[r_slots] == K_DEAD)
                    & covered & not_superseded & active)
    old_subjects = jnp.where(dead_retired, r_subj, n)
    tombstone = state.tombstone.at[old_subjects].max(True, mode="drop")
    if kind == K_ALIVE:
        tombstone = tombstone.at[
            jnp.where(active, jnp.clip(subjects, 0), n)].set(
            False, mode="drop")

    facts = FactTable(
        subject=state.facts.subject.at[wslots].set(subjects, mode="drop"),
        kind=state.facts.kind.at[wslots].set(jnp.uint8(kind), mode="drop"),
        incarnation=state.facts.incarnation.at[wslots].set(
            jnp.asarray(incarnations, jnp.uint32), mode="drop"),
        ltime=state.facts.ltime.at[wslots].set(
            jnp.asarray(ltimes, jnp.uint32), mode="drop"),
        valid=state.facts.valid.at[wslots].set(True, mode="drop"),
    )

    # bool[K]: ring slots overwritten this batch (their old fact retires)
    written = jnp.zeros((k,), bool).at[wslots].set(True, mode="drop")
    clear_words = pack_bits(written)                          # u32[W]

    # known: clear retired slots everywhere, then set each fact's bit at its
    # origin.  Bits are distinct within the batch and just cleared, so a
    # scatter-add is an OR.
    known = state.known & ~clear_words[None, :]
    words = wslots // 32
    bitmasks = jnp.where(active,
                         jnp.uint32(1) << (wslots % 32).astype(jnp.uint32),
                         jnp.uint32(0))
    known = known.at[worigins, jnp.where(active, words, 0)].add(
        bitmasks, mode="drop")

    stamp = state.stamp.at[worigins, wslots].set(
        round_u8(state.round), mode="drop")

    # sendable cache mirror (see inject_fact; flag-gated at trace time):
    # retire everywhere, age-0 bits at the origins
    sendable = state.sendable
    sendable_round = state.sendable_round
    if cfg.use_sendable_cache:
        sendable = sendable & ~clear_words[None, :]
        sendable = sendable.at[worigins, jnp.where(active, words, 0)].add(
            bitmasks, mode="drop")
    else:
        sendable_round = jnp.asarray(-1, jnp.int32)

    return state._replace(facts=facts, known=known, stamp=stamp,
                          tombstone=tombstone,
                          sendable=sendable, sendable_round=sendable_round,
                          next_slot=state.next_slot
                          + jnp.sum(active).astype(jnp.int32),
                          last_learn=bump_last_learn(
                              jnp.any(active), state.round,
                              state.last_learn))


#: below this, a flat top_k over all n scores is cheap; above it, top_k's
#: full sort dominates the round (measured 1.9 ms per call at 1M — three
#: calls per swim round) and the two-level groupwise pick wins (0.7 ms)
_PICK_FLAT_MAX = 1 << 16
#: number of strided groups for the two-level pick (top_k runs over this
#: many group maxima)
_PICK_GROUPS = 4096


def pick_bounded(candidates: jnp.ndarray, max_events: int, key: jax.Array):
    """Bounded selection: choose ≤``max_events`` of the candidate nodes
    (bool[N]) by randomized scoring.

    Returns ``(chosen bool[N], subjects i32[M], active bool[M])``; the
    active entries are a contiguous prefix — exactly the
    ``inject_facts_batch`` contract (real candidates score > 0, others 0,
    and selection sorts descending).

    Small n: one flat randomized top_k (unbiased).  Large n: two-level —
    the index space is split into ``_PICK_GROUPS`` groups, each group
    elects its max-score candidate in one elementwise pass, and top_k
    runs over only the G group maxima.  At most one winner per group per
    round is a selection bias; to keep any FIXED candidate set from
    being degenerate, the grouping LAYOUT alternates per round (keyed off
    the PRNG): *strided* groups (group j = indices ≡ j mod G — spreads
    contiguous id ranges: range partitions, rack failures) or
    *contiguous blocks* (group j = indices j·rows..(j+1)·rows — spreads
    arithmetic progressions: a set colliding mod G is spaced ≥ G apart,
    so blocks of rows < G hold at most one each).  No set collides under
    BOTH layouts, so an adversarial set drains at ≥ half the ideal rate
    (quantified in tests/test_device_plane.py::test_pick_bounded_adversarial_drain;
    analysis in DESIGN.md).  Un-picked candidates simply remain
    candidates for the next round (the max_events bound already defers
    extras).  Both layouts are pure reshapes — no gathers — preserving
    the win over the full 1M-element sort that made the flat top_k the
    single most expensive op in the swim round.
    """
    def topk_padded(scores: jnp.ndarray):
        # top_k requires k <= the axis size; clamp and pad the tail with
        # zero scores (inactive by the `vals > 0` predicate below)
        kk = min(max_events, scores.shape[0])
        vals, idx = jax.lax.top_k(scores, kk)
        if kk < max_events:
            vals = jnp.pad(vals, (0, max_events - kk))
            idx = jnp.pad(idx, (0, max_events - kk))
        return vals, idx

    n = candidates.shape[0]
    k_score, k_layout = jax.random.split(key)
    score = candidates.astype(jnp.float32) * (
        1.0 + jax.random.uniform(k_score, (n,)))
    if n <= _PICK_FLAT_MAX:
        vals, idx = topk_padded(score)
        active = vals > 0.0
        subjects = idx.astype(jnp.int32)
    else:
        g = _PICK_GROUPS
        # the blocks-vs-strided complementarity proof needs rows <= g
        # (a mod-g-colliding set is spaced g apart, so blocks of rows <= g
        # hold at most one member each); above n = g^2 (~16.7M) grow the
        # group count to the next power of two >= sqrt(n).  n is static
        # under jit, so this is trace-time Python.
        while (n + g - 1) // g > g:
            g *= 2
        rows = (n + g - 1) // g
        padded = score if rows * g == n else jnp.pad(score,
                                                     (0, rows * g - n))

        def strided(p):
            s2 = p.reshape(rows, g)     # column j = indices ≡ j mod g
            winner = (jnp.argmax(s2, axis=0).astype(jnp.int32) * g
                      + jnp.arange(g, dtype=jnp.int32))
            return jnp.max(s2, axis=0), winner

        def blocks(p):
            s2 = p.reshape(g, rows)     # row j = indices j*rows..+rows
            winner = (jnp.arange(g, dtype=jnp.int32) * rows
                      + jnp.argmax(s2, axis=1).astype(jnp.int32))
            return jnp.max(s2, axis=1), winner

        grp_max, grp_winner = jax.lax.cond(
            jax.random.bernoulli(k_layout), strided, blocks, padded)
        # at most one winner per group, so only min(max_events, G) picks
        # are possible; the tail comes back inactive
        vals, cols = topk_padded(grp_max)
        active = vals > 0.0
        subjects = grp_winner[cols]
    chosen = jnp.zeros((n,), bool).at[
        jnp.where(active, subjects, n)].set(True, mode="drop")
    return chosen, subjects, active


# -- the gossip round kernel -------------------------------------------------

def round_step(state: GossipState, cfg: GossipConfig,
               key: jax.Array, group=None) -> GossipState:
    """One gossip round: select packets, pull-exchange, Lamport-merge.

    Vectorized translation of the reference hot path: `get_broadcasts` drain
    (budget decrement) + `SerfDelegate::broadcast_messages` piggybacking +
    per-receiver `handle_*` first-sight rebroadcast decision
    (reference delegate.rs:317-384, base.rs:783-813).

    ``group`` (optional i32[N]) is the partition mask: packets only flow
    between nodes in the same group — the device analog of the reference's
    block-diagonal adjacency partition (SURVEY.md §7 stage 6).

    Skip-gated on ``round - last_learn < transmit_limit``: past that,
    every knower's derived age is >= the limit, the sending set is
    provably empty, and the whole select/exchange/merge is a bit-exact
    identity — a fully quiescent cluster (serf with an empty broadcast
    queue) pays only the round increment and the amortized clamp.  A new
    injection or merge bumps ``last_learn`` and re-opens the gate.
    """
    n, k, w = cfg.n, cfg.k_facts, cfg.words

    use_pallas = cfg.use_pallas
    if use_pallas:
        from serf_tpu.ops import round_kernels
        use_pallas = round_kernels.pallas_ok(n, k)

    def active(state):
        if use_pallas:
            alive_u8 = state.alive[:, None].astype(jnp.uint8)
            # phase 1: pack sending bits — one read-only pass over the
            # stamp plane + known words (derived age, no tick anywhere).
            # The pallas path neither reads nor maintains the sendable
            # cache (it leaves sendable_round stale, which is safe).
            packets = round_kernels.select_packets(
                state.stamp, state.known, alive_u8, cfg.transmit_limit,
                state.round)
        elif cfg.use_sendable_cache:
            # 1. packet selection: use the cached predicate when valid
            #    (one 8 MB word-plane read at 1M instead of the 64 MB
            #    stamp-plane pass), else recompute from stamps
            packets = jax.lax.cond(
                state.sendable_round == state.round,
                lambda s: jnp.where(s.alive[:, None], s.sendable,
                                    jnp.uint32(0)),
                lambda s: pack_bits(sending_mask(s, cfg)),
                state)
        else:
            # 1. packet selection: known facts with remaining transmit
            #    budget (derived age < limit), from alive nodes
            sending = sending_mask(state, cfg)
            packets = pack_bits(sending)                      # u32[N, W]

        # 3. pull-exchange: each alive node samples `fanout` peers and
        #    ORs their packet words
        if cfg.peer_sampling == "rotation":
            # fanout random rotations shared by all nodes: peer reads are
            # contiguous slices, no gather (GossipConfig.peer_sampling).
            # The doubled arrays are hoisted across the fanout slices —
            # ONE materialization by construction (the byte model's
            # "concat once" term, accounting.py)
            offs = sample_offsets(key, cfg.fanout, n)
            doubled = jnp.concatenate([packets, packets], axis=0)
            dgroup = (jnp.concatenate([group, group], axis=0)
                      if group is not None else None)
            incoming = jnp.zeros_like(packets)
            for f in range(cfg.fanout):
                contrib = rolled_rows(packets, offs[f], doubled=doubled)
                if group is not None:
                    allowed = rolled_rows(group, offs[f],
                                          doubled=dgroup) == group
                    contrib = jnp.where(allowed[:, None], contrib,
                                        jnp.uint32(0))
                incoming = incoming | contrib
        else:
            srcs = jax.random.randint(key, (n, cfg.fanout), 0, n)
            gathered = packets[srcs]                          # u32[N, F, W]
            if group is not None:
                allowed = (group[srcs] == group[:, None])     # bool[N, F]
                gathered = jnp.where(allowed[:, :, None], gathered,
                                     jnp.uint32(0))
            incoming = jax.lax.reduce(gathered, jnp.uint32(0),
                                      jnp.bitwise_or, (1,))   # u32[N, W]

        if use_pallas:
            # phases 4+5 fused: learn — set known bits and stamp newly
            # learned facts with the post-increment round (first visible
            # at age 0 next round); nothing ticks.  "learned anything" is
            # definitional (output vs input known) so it can never desync
            # from whatever the kernel's learn semantics are.
            known, stamp = round_kernels.merge_incoming(
                state.known, incoming, alive_u8, state.stamp,
                state.round + 1)
            learned_any = jnp.any(known != state.known)
            # the kernel learns without maintaining the cache — a later
            # cached selection on this state would miss those learns, so
            # invalidate (the pallas path always selects from stamps)
            sendable = state.sendable
            sendable_round = jnp.asarray(-1, jnp.int32)
        else:
            # 4. merge: learn facts we did not know; dead learn nothing
            alive_col = state.alive[:, None]
            new_words = incoming & ~state.known & jnp.where(
                alive_col, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
            known = state.known | new_words
            learned_any = jnp.any(new_words != 0)

            # 5. the round's only N×K write: stamp newly learned facts
            #    with the post-increment round — their derived age is 0
            #    at the next round's selection, exactly the old age-plane
            #    reset; everyone else's age advances for free because
            #    `round` advanced.  Gated on learned_any: with zero learns
            #    the where is a bit-exact identity, and skipping it saves
            #    the round's biggest single pass (stamp R+W, 128 MB at
            #    1M×64) during the fully-disseminated window the gossip
            #    gate hasn't closed yet (see serf_tpu/models/accounting.py).
            #    While the stamp plane is streaming through this pass
            #    anyway, the sendable cache for round+1 is recomputed in
            #    the same fusion — expiry transitions included — which is
            #    the only place the cache's validity round advances.
            def stamp_learns(_):
                new_mask = unpack_bits(new_words, k)          # bool[N, K]
                stamp2 = jnp.where(new_mask, round_u8(state.round + 1),
                                   state.stamp)
                if cfg.use_sendable_cache:
                    kb = unpack_bits(known, k)
                    age_next = round_u8(state.round + 1) - stamp2
                    send2 = pack_bits(
                        kb & (age_next < jnp.uint8(cfg.transmit_limit)))
                    sr2 = jnp.asarray(state.round + 1, jnp.int32)
                else:
                    # learned without mirroring: mixed-flag hygiene
                    send2 = state.sendable
                    sr2 = jnp.asarray(-1, jnp.int32)
                return stamp2, send2, sr2

            stamp, sendable, sendable_round = jax.lax.cond(
                learned_any, stamp_learns,
                lambda _: (state.stamp, state.sendable,
                           state.sendable_round), None)
        last_learn = bump_last_learn(learned_any, state.round + 1,
                                     state.last_learn)
        return known, stamp, last_learn, sendable, sendable_round

    def quiet(state):
        return (state.known, state.stamp, state.last_learn,
                state.sendable, state.sendable_round)

    known, stamp, last_learn, sendable, sendable_round = jax.lax.cond(
        state.round - state.last_learn < cfg.transmit_limit,
        active, quiet, state)

    # amortized wraparound guard (full-plane pass 1/CLAMP_EVERY rounds);
    # runs in BOTH branches — the clamp is what keeps mod-256 stamp ages
    # from wrapping back under the thresholds while the cluster is quiet.
    # Cache-safe: the clamp only re-pins stamps whose derived age exceeds
    # AGE_PIN (> transmit_limit by config validation), i.e. cells that
    # are non-sendable before AND after — the sendable invariant holds.
    stamp = clamp_stamps(known, stamp, state.round + 1, k)
    return state._replace(known=known, stamp=stamp, last_learn=last_learn,
                          sendable=sendable, sendable_round=sendable_round,
                          round=state.round + 1)


def run_rounds(state: GossipState, cfg: GossipConfig, key: jax.Array,
               num_rounds: int) -> GossipState:
    """lax.scan driver: the whole simulation stays on-device."""

    def body(carry, subkey):
        return round_step(carry, cfg, subkey), ()

    keys = jax.random.split(key, num_rounds)
    final, _ = jax.lax.scan(body, state, keys)
    return final


def push_round_step(state: GossipState, cfg: GossipConfig,
                    key: jax.Array) -> GossipState:
    """Exact *push*-gossip round as MXU matmuls (the north star's "SWIM as a
    GNN-style message-passing kernel", BASELINE.json).

    Each node picks ``fanout`` targets and SENDS its packet; delivery is a
    boolean-semiring matmul: unpack packets to a bit plane ``B[N, K]``,
    build the round's adjacency ``A[N, N]`` from the sampled targets, and
    ``incoming = (Aᵀ @ B) > 0`` — dense matmuls the MXU eats directly.
    O(N²) per round, so this is the conformance/small-N mode (the reference
    push semantics bit-for-bit at the round level); the pull kernel in
    ``round_step`` is the O(N·F) scale mode.  Budget accounting is
    identical (one decrement per selected fact per round).
    """
    n, k = cfg.n, cfg.k_facts

    sending = sending_mask(state, cfg)                        # bool[N, K]

    targets = jax.random.randint(key, (n, cfg.fanout), 0, n)  # i32[N, F]
    # adjacency: A[src, dst] = 1 if src sends to dst this round
    adj = jnp.zeros((n, n), jnp.float32)
    adj = adj.at[jnp.arange(n)[:, None], targets].set(1.0)
    adj = adj * state.alive[:, None].astype(jnp.float32)      # dead don't send

    bits = sending.astype(jnp.float32)                        # f32[N, K]
    counts = jnp.matmul(adj.T, bits,
                        preferred_element_type=jnp.float32)   # MXU [N, K]
    incoming = counts > 0.0

    alive_col = state.alive[:, None]
    new_mask = incoming & ~unpack_bits(state.known, k) & alive_col
    known = state.known | pack_bits(new_mask)
    stamp = jnp.where(new_mask, round_u8(state.round + 1), state.stamp)
    stamp = clamp_stamps(known, stamp, state.round + 1, k)
    last_learn = bump_last_learn(jnp.any(new_mask), state.round + 1,
                                 state.last_learn)
    # this conformance-mode kernel learns without maintaining the
    # sendable cache — invalidate so a later cached selection can't read
    # a plane that misses these learns
    return state._replace(known=known, stamp=stamp, last_learn=last_learn,
                          sendable_round=jnp.asarray(-1, jnp.int32),
                          round=state.round + 1)


# -- metrics -----------------------------------------------------------------

def coverage(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """Fraction of alive nodes that know each fact: f32[K]."""
    known = unpack_bits(state.known, cfg.k_facts)             # bool[N, K]
    alive = state.alive[:, None]
    num = jnp.sum(known & alive, axis=0).astype(jnp.float32)
    den = jnp.maximum(jnp.sum(state.alive), 1).astype(jnp.float32)
    return num / den


def fully_disseminated(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """bool[K]: every alive node knows the fact (for valid facts)."""
    cov = coverage(state, cfg)
    return jnp.where(state.facts.valid, cov >= 1.0, True)


def emit_gossip_metrics(state: GossipState, cfg: GossipConfig,
                        labels=None) -> dict:
    """Emit device-plane dissemination gauges onto the process sink.

    The model runs under jit where Python-side counters cannot fire, so
    observability is pull-based: call this between scans (bench.py does,
    after each timed block) and it summarizes the HBM-resident state into
    host scalars — one device->host sync plus an N×K unpack for coverage
    and fan-out, so never call it inside a jitted round.  Returns the
    emitted ``{name: value}`` dict so callers can embed it in artifacts.
    """
    from serf_tpu.utils import metrics

    valid = state.facts.valid
    n_valid = jnp.maximum(jnp.sum(valid), 1).astype(jnp.float32)
    mean_cov = jnp.sum(jnp.where(valid, coverage(state, cfg), 0.0)) / n_valid
    # dissemination fan-out: packets each alive node would select this
    # round (the transmit-limited queue's aggregate depth, vectorized)
    fan_out = jnp.sum(sending_mask(state, cfg)).astype(jnp.float32) \
        / jnp.maximum(jnp.sum(state.alive), 1).astype(jnp.float32)
    # one device_get for the whole dict: async-copies every leaf, then a
    # single blocking wait — not one round-trip per metric
    vals = jax.device_get({
        "serf.model.gossip.round": state.round,
        "serf.model.gossip.alive": jnp.sum(state.alive),
        "serf.model.gossip.facts-valid": jnp.sum(valid),
        "serf.model.gossip.coverage": mean_cov,
        "serf.model.gossip.fan-out": fan_out,
        "serf.model.gossip.tombstones": jnp.sum(state.tombstone),
    })
    vals = {name: float(v) for name, v in vals.items()}
    for name, v in vals.items():
        metrics.gauge(name, v, labels)
    return vals
