"""Device-plane gossip dissemination: the cluster as arrays in HBM.

This is the TPU-native re-design of serf's dissemination machinery
(SURVEY.md §7, stage 3/4).  The mapping from the reference:

- serf's broadcast queues + ring dedup buffers (serf-core/src/broadcast.rs,
  base.rs:750-837) become a bounded **fact table**: K slots of immutable
  facts ``(subject, kind, incarnation, ltime)``.  New facts overwrite ring
  slots, exactly like the reference's ``buffer[ltime % len]`` dedup cells.
- each simulated node's state is a row: a packed bitset of which facts it
  knows (``known``: N×W uint32) and a **nibble-packed learn stamp**
  (``stamp``: N×K/2 uint8 — two 4-bit stamps per byte; each nibble is the
  learn round divided by ``STAMP_UNIT`` (=4) mod 16, valid only where the
  known bit is set).  A fact's knowledge age and its remaining transmit
  budget (the TransmitLimitedQueue, vectorized) are DERIVED in
  quarter-round ticks: ``q_age = (round//4 - stamp) mod 16`` (``mod_age``)
  and ``budget_ticks = max(0, transmit_limit//4 - q_age)``
  (``budgets_of``).  Stamps are written once per LEARN event, never
  ticked — so neither the per-round budget decrement nor fact retirement
  rewrites the stamp plane (see ``GossipState``).  Protocol windows are
  thereby quantized to ``STAMP_UNIT`` rounds (a fact learned mid-quarter
  expires up to 3 rounds early) — the deliberate trade that halves the
  round's dominant HBM plane.
- a gossip round = sample ``fanout`` peers per node, gather their packed
  packet words, bitwise-OR, then a masked Lamport-style merge — pure
  elementwise math plus one gather, which is exactly what the MXU-era memory
  system wants.  No scatter: the round uses *pull* sampling (each node
  pulls from ``fanout`` random peers), which converges like push-gossip and
  keeps the kernel gather-only; transmit budgets still decrement once per
  round per selected fact, matching the reference's drain-once-per-tick
  semantics (memberlist gossip).
- packet-byte budgets degenerate to the fact-table bound K (a fact slot is
  O(16B), K·16B < the reference's 1400B UDP budget for K ≤ 64).

Everything here is jit-compatible with static shapes; dynamic membership is
a liveness mask (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

# fact kinds (precedence for view resolution: higher wins at equal
# incarnation; alive refutes suspect at *higher* incarnation only)
K_NONE = 0
K_JOIN = 1        # serf join intent (ltime-ordered)
K_LEAVE = 2       # serf leave intent (ltime-ordered)
K_ALIVE = 3       # swim alive (incarnation-ordered; refutes suspect/dead)
K_SUSPECT = 4     # swim suspicion (starts a timer at each knower)
K_DEAD = 5        # swim death declaration
K_USER_EVENT = 6  # user event broadcast (subject = event id)
K_QUERY = 7       # query scatter (subject = query slot id; models/query.py)


class FactTable(NamedTuple):
    """K immutable dissemination facts (the global 'what is being gossiped')."""

    subject: jnp.ndarray       # i32[K] node id or event id
    kind: jnp.ndarray          # u8[K]
    incarnation: jnp.ndarray   # u32[K]
    ltime: jnp.ndarray         # u32[K]
    valid: jnp.ndarray         # bool[K]


class GossipState(NamedTuple):
    """The whole simulated cluster, struct-of-arrays.

    There is deliberately no transmit-budget plane and no stored age plane:
    a fact's knowledge age is fully determined by its learn stamp —
    ``q_age = (round >> STAMP_SHIFT) - stamp mod 16`` quarter-round ticks
    where the known bit is set (garbage where it isn't) — and its
    remaining transmit budget by that age: ``budget_ticks =
    max(0, transmit_limit_q - q_age)`` (learn: full budget, q_age=0; one
    transmit per round as long as q_age < limit_q).  Deriving both
    (``age_of``/``budgets_of``) means the stamp plane is written only on
    LEARN events (one full-plane select in the round's merge) — the
    round-1 stored-budget plane's decrement pass AND the stored-age
    plane's saturating tick AND the per-injection full-plane retirement
    rewrite (64 MB × 3-4 injections/round at 1M) are all gone; retirement
    is just the known-bit clear.

    The stamp plane itself is nibble-packed when ``cfg.pack_stamp`` (the
    default): u8[N, ⌈K/2⌉], fact ``k`` in byte ``k//2`` (even ``k`` = low
    nibble) — 32 MB instead of 64 MB at 1M×64, halving the round's
    dominant HBM pass.  ``pack_stamp=False`` stores the same 4-bit values
    un-packed in u8[N, K]; the two flavors are bit-exact in every
    protocol output (tests/test_stamp_packing.py pins it) — the flag
    exists for that A/B and as an escape hatch.  (Round-2 rejected u4
    packing because round-granular thresholds like transmit_limit=28
    exceed 15; quarter-round ticks are what make 4 bits sufficient:
    every threshold lives in q-units ≤ AGE_PIN_Q.)

    The mod-16 q-stamp wraps every 64 rounds; every pass that streams
    the stamp plane re-pins stale stamps to ``AGE_PIN_Q`` (the merge's
    learn pass does it for free), and ``round_step`` runs a standalone
    clamp pass only when no streaming pass has run for ``CLAMP_EVERY``
    rounds (``GossipState.last_clamp``) — so a fact's derived q-age can
    never wrap back under ``transmit_limit_q``/the suspicion window,
    both of which config validation bounds to ``AGE_PIN_Q``.

    One semantic consequence, closer to the reference than the stored
    budget plane was: a node that is down ages past its budgets, so a
    rejoiner does not resume retransmitting stale facts (the reference's
    restarted node comes back with an empty broadcast queue,
    serf-core/src/serf/base.rs:62-344 — queues are rebuilt, not restored).
    """

    facts: FactTable
    known: jnp.ndarray          # u32[N, W]  packed known-fact bitset
    stamp: jnp.ndarray          # u8[N, K/2] (packed) or u8[N, K]: 4-bit
                                #            learn-quarter stamps, valid
                                #            only where known (see
                                #            stamp_nibbles/pack_stamp)
    alive: jnp.ndarray          # bool[N]    ground-truth liveness
    incarnation: jnp.ndarray    # u32[N]     ground-truth own incarnation
    round: jnp.ndarray          # i32 scalar
    next_slot: jnp.ndarray      # i32 scalar ring cursor for fact injection
    last_learn: jnp.ndarray     # i32 scalar round of the most recent learn
                                # event ANYWHERE (inject or merge).  Once
                                # `round - last_learn >= transmit_limit`,
                                # every knower's derived age is >= the
                                # limit, so NO fact is sendable and the
                                # gossip exchange is provably an identity
                                # — round_step skips it under lax.cond
                                # (serf's empty broadcast queue sends
                                # nothing).  Every path that writes
                                # stamps/known must update this scalar.
    tombstone: jnp.ndarray      # bool[N]    durable per-subject death
                                # record: set when a fully-disseminated
                                # K_DEAD fact RETIRES from the ring
                                # (slot overwritten), cleared by any
                                # K_ALIVE injection for the subject
                                # (refutation / rejoin).  The device
                                # analog of the reference's member table
                                # holding FAILED after the broadcast
                                # queue drains (base.rs:1375-1440): ring
                                # facts are transient dissemination
                                # state, but the cluster must not FORGET
                                # a death when the slot recycles — under
                                # sustained load the ring cycles every
                                # k_facts/rate rounds.  A death that
                                # retires only PARTIALLY disseminated is
                                # dropped (documented compression: a
                                # per-subject bit cannot represent
                                # per-knower splits once the per-knower
                                # evidence is gone; the detector will
                                # re-suspect such a subject).
    sendable: jnp.ndarray       # u32[N, W]  packed CACHE of the selection
                                # predicate `known & (mod_age < limit_q)`
                                # (alive NOT folded in — liveness changes
                                # externally).  Valid ONLY when
                                # sendable_round == round; see below.
    sendable_round: jnp.ndarray  # i32 scalar: the round `sendable` is
                                # valid for (-1 = never).  INVARIANT:
                                # sendable_round == R implies
                                # sendable & known ==
                                # pack(known & (mod_age(R) < limit_q)) —
                                # the cache may hold STALE bits for
                                # retired ring slots; readers AND with
                                # `known` (whose retirement clear is
                                # mandatory anyway), which is why inject
                                # no longer pays a second full-plane
                                # retirement pass on this cache.
                                # Writers: the merge's learn pass
                                # recomputes the full plane for round+1
                                # (the only place the validity round
                                # advances — expiry transitions are only
                                # visible while the stamp plane is being
                                # streamed anyway); inject/push_pull OR
                                # their age-0 learn bits in, which
                                # preserves validity for the SAME round
                                # (and is harmless on a stale plane — a
                                # stale plane is never read).  Selection
                                # uses the cache only when valid, else
                                # falls back to the stamp-plane recompute
                                # (accounting.py quantifies the
                                # 32 MB/round this saves in the sustained
                                # regime at 1M).
    last_clamp: jnp.ndarray     # i32 scalar: last round a pass streamed
                                # (and therefore clamped) the stamp
                                # plane.  The merge/push learn passes
                                # fold the wrap clamp in for free and
                                # bump this; round_step runs a standalone
                                # clamp pass only when
                                # round - last_clamp >= CLAMP_EVERY, so
                                # under sustained load the standalone
                                # pass never fires.
    slot_round: jnp.ndarray     # i32[K]  round each ring slot was last
                                # WRITTEN by an injection — the overflow
                                # accountant's clock (O(K): bytes-free
                                # next to the N-sized planes)
    overflow: jnp.ndarray       # u32 scalar: cumulative count of facts
                                # clobbered while still inside their
                                # transmit window — injection recycled
                                # the slot before the fact could finish
                                # disseminating.  The device analog of
                                # the host plane's shed counters
                                # (``serf.overload.device_dropped`` via
                                # emit_gossip_metrics): bounded
                                # fact-injection ACCOUNTS its overflow
                                # instead of silently clobbering when
                                # events_per_round bursts past ring
                                # capacity.
    injected: jnp.ndarray       # u32 scalar: cumulative facts injected
                                # into the ring by ANY path (executor
                                # events, SWIM suspicions/declarations,
                                # refutations, churn).  The other half
                                # of the overload ledger: overflow can
                                # never exceed it, and
                                # ``injected - overflow`` is the count
                                # that got a full dissemination window.
    overlay: jnp.ndarray        # u32[N, W]  learned-since-flush word
                                # overlay (quarter-deferred stamp
                                # flushes, ``cfg.stamp_flush_unit``): a
                                # set bit marks a fact learned by a
                                # mid-cohort merge/push-pull whose
                                # stamp nibble has NOT been written yet
                                # — its effective q-age is 0 and every
                                # mod_age reader (selection, declare,
                                # believed_dead, the cache recompute)
                                # reads through it.  Cleared by the
                                # cohort flush, which writes the
                                # pending nibbles in one streaming
                                # pass.  All-zero (inert) on the
                                # per-round path (stamp_flush_unit=1).
    last_flush: jnp.ndarray     # i32 scalar: the ``next``-round value
                                # of the most recent cohort flush (the
                                # merge that streamed the stamp plane
                                # and cleared the overlay).  Powers the
                                # flush-due predicate under a traced
                                # STAMP_UNIT knob and the watchdog's
                                # ``stamp_staleness_ok`` row:
                                # ``last_learn > last_flush`` is the
                                # scalar proxy for "overlay nonempty".
                                # Stays 0 (inert) on the per-round
                                # path.


@dataclasses.dataclass(frozen=True)
class GossipConfig:
    """Static configuration (shapes + protocol constants)."""

    n: int                      # number of simulated nodes
    k_facts: int = 64           # fact-table capacity (ring)
    fanout: int = 3             # gossip_nodes
    retransmit_mult: int = 4    # transmit budget = mult * ceil(log10(n+1))
    use_pallas: bool = False    # Pallas kernels for phases 1+3
    #: with ``use_pallas``: dispatch the FUSED kernel family (ops.
    #: fused_select_cached / ops.fused_merge — cache-maintaining, one
    #: streaming pass per plane per round, shard_map-ready, bit-exact
    #: with the XLA path on every GossipState leaf).  False keeps the
    #: PR-3 standalone kernels (cache-invalidating, single-device) — the
    #: A/B flavor and escape hatch the bench measures against.
    fused_kernels: bool = True
    #: "iid": every node samples uniform peers each round — the direct
    #: analog of memberlist's random gossip targets, but each sample is a
    #: random-index gather/scatter, which XLA lowers to a SERIAL loop on
    #: TPU (~10 ms per 1M-row op — measured; the whole round budget is
    #: <1 ms).  "rotation": each round draws ``fanout`` random rotation
    #: offsets shared by all nodes; node i's f-th peer is (i+off_f) mod n,
    #: so every peer read is one contiguous dynamic-slice (``rolled_rows``)
    #: and every inverse ("who contacted me") is analytic.  A fresh random
    #: cyclic matching per round is the vectorized analog of memberlist's
    #: shuffled round-robin probe list and converges like random gossip
    #: (random Cayley-graph expanders); it is the intended mode at scale.
    peer_sampling: str = "iid"
    #: use the packed ``sendable`` cache for packet selection when valid
    #: (GossipState.sendable_round): saves the selection's full stamp-
    #: plane read (32 MB/round at 1M) whenever the previous round's merge
    #: learned anything — i.e. nearly always under sustained load.
    #: Bit-exact either way (tests/test_sendable_cache.py pins it);
    #: the flag exists for that A/B and as an escape hatch.
    use_sendable_cache: bool = True
    #: nibble-pack the stamp plane (u8[N, K/2], two 4-bit stamps/byte)
    #: instead of one byte per fact.  Same 4-bit quarter-round semantics
    #: either way; bit-exact protocol outputs pinned by
    #: tests/test_stamp_packing.py.  Default ON: it halves the round's
    #: dominant HBM plane (accounting.py).
    pack_stamp: bool = True
    #: quarter-deferred stamp flushes (README "Deferred stamp flushes"):
    #: rounds per stamp-plane flush cohort, in {1, 2, 4} (must divide
    #: STAMP_UNIT so a cohort never spans a quarter boundary).  1 (the
    #: default) is today's per-round behavior — leaf-for-leaf identical,
    #: the overlay/last_flush leaves ride inert.  >1 defers the merge's
    #: stamp R+W to one streaming flush per cohort; mid-cohort learns
    #: land in the ``overlay`` word bitplane, which every q-age reader
    #: reads through — derived ages, membership views and detection
    #: outcomes stay bit-exact with the per-round path at EVERY round,
    #: only the raw stamp plane is stale <= STAMP_UNIT-1 rounds
    #: mid-cohort (the deliberate semantics change that breaks the
    #: 217 MB/round floor; accounting.round_traffic(stamp_deferred=)
    #: prices it).  Under adaptive control the live unit is the
    #: ``stamp_unit`` knob (log2, control/device.py) seeded from this
    #: value.
    stamp_flush_unit: int = 1

    def __post_init__(self):
        if self.peer_sampling not in ("iid", "rotation"):
            raise ValueError(
                f"unknown peer_sampling {self.peer_sampling!r}")
        if self.stamp_flush_unit not in (1, 2, 4):
            # units must divide STAMP_UNIT: a flush cohort then never
            # spans a quarter boundary, so every pending overlay bit
            # shares the flush's write quarter (round_q(flush-1)) and
            # the deferred write is value-exact
            raise ValueError(
                f"stamp_flush_unit {self.stamp_flush_unit} must be one "
                f"of (1, 2, 4) — a divisor of STAMP_UNIT={STAMP_UNIT}, "
                "so flush cohorts never span a stamp quarter")
        if self.transmit_limit_q > AGE_PIN_Q:
            # derived q-ages are pinned at AGE_PIN_Q by the stamp clamp;
            # a limit above the pin would let pinned (very old) facts
            # re-enter the sending set
            raise ValueError(
                f"transmit_limit {self.transmit_limit} exceeds "
                f"{AGE_PIN_Q * STAMP_UNIT} (the 4-bit stamp age pin; "
                f"lower retransmit_mult)")

    @property
    def words(self) -> int:
        assert self.k_facts % 32 == 0, "k_facts must be a multiple of 32"
        return self.k_facts // 32

    @property
    def transmit_limit(self) -> int:
        import math
        return self.retransmit_mult * max(1, math.ceil(math.log10(self.n + 1)))

    @property
    def transmit_limit_q(self) -> int:
        """The transmit window in quarter-round stamp ticks (the unit
        every age predicate compares in).  Exact when ``transmit_limit``
        is a multiple of STAMP_UNIT (the default retransmit_mult=4
        always is); otherwise it rounds UP — which is why every
        round-unit consumer must gate on :attr:`transmit_window_rounds`,
        not ``transmit_limit``."""
        return -(-self.transmit_limit // STAMP_UNIT)

    @property
    def transmit_window_rounds(self) -> int:
        """Upper bound of the q-quantized send window in ROUNDS
        (= STAMP_UNIT * transmit_limit_q ≥ transmit_limit).  THE value
        round-unit logic must use: a fact learned at round L satisfies
        ``q_age >= transmit_limit_q`` for every round ≥ L +
        transmit_window_rounds, so the quiet gate keyed on this bound is
        provably empty-safe for ANY retransmit_mult (gating on the raw
        ``transmit_limit`` would close the gate up to 3 rounds early
        when the limit is not a multiple of STAMP_UNIT, silently
        dropping still-budgeted transmissions)."""
        return STAMP_UNIT * self.transmit_limit_q

    @property
    def stamp_cols(self) -> int:
        """Byte columns of the stamp plane for this flavor."""
        return self.k_facts // 2 if self.pack_stamp else self.k_facts

    @property
    def stamp_deferred(self) -> bool:
        """True when the quarter-deferred flush machinery is COMPILED
        (``stamp_flush_unit > 1``) — the static gate every deferred
        branch keys on, so the unit=1 path traces exactly today's
        jaxpr (the leaf-for-leaf identity the tier-1 suite pins)."""
        return self.stamp_flush_unit > 1


#: log2 of the stamp resolution: stamps record the learn round in units
#: of STAMP_UNIT = 1 << STAMP_SHIFT rounds.  Protocol windows quantize
#: to this unit (a fact learned mid-quarter expires up to STAMP_UNIT-1
#: rounds early); in exchange every age threshold fits a 4-bit nibble.
STAMP_SHIFT = 2
STAMP_UNIT = 1 << STAMP_SHIFT
#: derived q-ages are pinned here by the stamp clamp; must be >= every
#: q-age threshold the protocol compares against (transmit_limit_q, the
#: suspicion window in q-units — both config-validated against it)
AGE_PIN_Q = 8
#: max rounds between stamp-clamping passes (GossipState.last_clamp).
#: Correctness bound: a known fact's derived q-age is <= AGE_PIN_Q right
#: after a clamp, so it reaches at most AGE_PIN_Q + CLAMP_EVERY/STAMP_UNIT
#: = 12 < 16 before the next one — it can never wrap back under the
#: thresholds.  Cost: free under sustained load (the merge learn pass
#: clamps while it streams); one standalone half-plane pass per
#: CLAMP_EVERY rounds otherwise (amortized ~2 MB/round at 1M×64).
CLAMP_EVERY = 16


def make_state(cfg: GossipConfig) -> GossipState:
    n, k, w = cfg.n, cfg.k_facts, cfg.words
    facts = FactTable(
        subject=jnp.full((k,), -1, jnp.int32),
        kind=jnp.zeros((k,), jnp.uint8),
        incarnation=jnp.zeros((k,), jnp.uint32),
        ltime=jnp.zeros((k,), jnp.uint32),
        valid=jnp.zeros((k,), bool),
    )
    return GossipState(
        facts=facts,
        known=jnp.zeros((n, w), jnp.uint32),
        stamp=jnp.zeros((n, cfg.stamp_cols), jnp.uint8),
        alive=jnp.ones((n,), bool),
        incarnation=jnp.ones((n,), jnp.uint32),
        round=jnp.asarray(0, jnp.int32),
        next_slot=jnp.asarray(0, jnp.int32),
        last_learn=jnp.asarray(0, jnp.int32),
        tombstone=jnp.zeros((n,), bool),
        sendable=jnp.zeros((n, w), jnp.uint32),
        sendable_round=jnp.asarray(-1, jnp.int32),
        last_clamp=jnp.asarray(0, jnp.int32),
        # far in the past: writing over a never-used slot is not overflow
        slot_round=jnp.full((k,), -(1 << 30), jnp.int32),
        overflow=jnp.asarray(0, jnp.uint32),
        injected=jnp.asarray(0, jnp.uint32),
        overlay=jnp.zeros((n, w), jnp.uint32),
        last_flush=jnp.asarray(0, jnp.int32),
    )


def round_q(round_) -> jnp.ndarray:
    """u8 scalar in [0, 16): the 4-bit stamp value for a round counter —
    the round's quarter index mod 16."""
    return ((jnp.asarray(round_, jnp.int32) >> STAMP_SHIFT) & 0xF
            ).astype(jnp.uint8)


def stamp_nibbles(stamp: jnp.ndarray, k: int, packed: bool) -> jnp.ndarray:
    """u8[..., K] of 4-bit stamp values, whatever the storage flavor.
    Packed: byte ``k//2`` holds fact ``k`` (even = low nibble)."""
    if not packed:
        return stamp
    lo = stamp & jnp.uint8(0xF)
    hi = stamp >> 4
    *lead, cols = stamp.shape
    return jnp.stack([lo, hi], axis=-1).reshape(*lead, k)


def pack_stamp_nibbles(nib: jnp.ndarray, packed: bool) -> jnp.ndarray:
    """Inverse of :func:`stamp_nibbles`: u8[..., K] 4-bit values back to
    the storage flavor."""
    if not packed:
        return nib
    lo = nib[..., 0::2]
    hi = nib[..., 1::2]
    return (lo & jnp.uint8(0xF)) | (hi << 4)


def learn_pairs_words(new_words: jnp.ndarray, k: int):
    """u32[..., W] per-fact bits -> (lo, hi) bool[..., K/2] per BYTE
    column of the packed stamp plane: byte ``c`` holds facts ``2c`` (low
    nibble) and ``2c+1`` (high) = bits ``2*(c%16)`` / ``2*(c%16)+1`` of
    word ``c//16``.  A contiguous ``repeat`` + elementwise shifts — the
    byte-space bridge that lets every packed-plane pass avoid the
    K-order interleave (a layout shuffle XLA materializes; measured ~1.5×
    on the CPU round before this path existed)."""
    c = k // 2
    rep = jnp.repeat(new_words, 16, axis=-1)              # (..., K/2)
    shifts = 2 * (jnp.arange(c, dtype=jnp.uint32) % 16)
    pair = (rep >> shifts) & jnp.uint32(3)
    return (pair & 1).astype(bool), (pair >> 1).astype(bool)


def pack_pred_words(ok_lo: jnp.ndarray, ok_hi: jnp.ndarray) -> jnp.ndarray:
    """Inverse bridge: per-nibble predicate bits bool[..., K/2] ->
    u32[..., W] per-fact words (fact ``2c+p`` = bit ``2*(c%16)+p`` of
    word ``c//16``) — weighted shifts + a contiguous group sum."""
    *lead, c = ok_lo.shape
    p = jnp.arange(c, dtype=jnp.uint32) % 16
    weighted = ((ok_lo.astype(jnp.uint32) << (2 * p))
                + (ok_hi.astype(jnp.uint32) << (2 * p + 1)))
    return jnp.sum(weighted.reshape(*lead, c // 16, 16), axis=-1,
                   dtype=jnp.uint32)


def nibble_age_pred_words(lo: jnp.ndarray, hi: jnp.ndarray, round_,
                          threshold, ge: bool = False) -> jnp.ndarray:
    """u32[..., W] of per-fact ``q_age < threshold`` (or ``>=`` with
    ``ge=True``) bits from the packed plane's nibble halves — THE one
    definition of the wrapping 4-bit age compare for every packed-flavor
    XLA site (selection, the learn pass's cache recompute, declare's
    expiry scan); the pallas kernels carry the same arithmetic in their
    own fused form."""
    rq = round_q(round_)
    q_lo = (rq - lo) & jnp.uint8(0xF)
    q_hi = (rq - hi) & jnp.uint8(0xF)
    t = jnp.uint8(threshold)
    if ge:
        return pack_pred_words(q_lo >= t, q_hi >= t)
    return pack_pred_words(q_lo < t, q_hi < t)


def clamp_learn_bytes(stamp: jnp.ndarray, new_words: jnp.ndarray, round_,
                      k: int):
    """Packed-flavor clamp + learn-write, per byte column: re-pin
    wrap-stale nibbles and stamp newly learned facts (``new_words``)
    with ``round_``'s quarter.  Returns ``(bytes', lo', hi')`` — callers
    derive cache predicates from the nibble halves.  THE one copy of the
    streaming-pass arithmetic (learn_stamp_pass and push_pull's reduced
    variant both route through it)."""
    rq = round_q(round_)
    lo = clamp_nibbles(stamp & jnp.uint8(0xF), round_)
    hi = clamp_nibbles(stamp >> 4, round_)
    lo_learn, hi_learn = learn_pairs_words(new_words, k)
    lo = jnp.where(lo_learn, rq, lo)
    hi = jnp.where(hi_learn, rq, hi)
    return lo | (hi << 4), lo, hi


def mod_age(state: GossipState, cfg: GossipConfig, round_=None
            ) -> jnp.ndarray:
    """u8[N, K]: quarter-round ticks since learned via wrapping 4-bit
    subtraction.  VALID ONLY where the known bit is set — callers must
    gate on the ``known`` bitset (every protocol predicate already
    does).

    Deferred-flush flavor (``cfg.stamp_flush_unit > 1``): cells whose
    overlay bit is set were learned since the last cohort flush — their
    stamp nibble is stale/unwritten and their TRUE q-age is 0 (a cohort
    never spans a quarter boundary, so a mid-cohort learn is always in
    the current quarter).  THE one overlay read-through for every
    bool-plane age consumer (sending_mask, believer_counts, the
    unpacked declare scan, budgets_of/age_of); packed word-space sites
    amend their ``nibble_age_pred_words`` result with the overlay words
    directly (select_words, declare's packed scan)."""
    r = state.round if round_ is None else round_
    nib = stamp_nibbles(state.stamp, cfg.k_facts, cfg.pack_stamp)
    age = (round_q(r) - nib) & jnp.uint8(0xF)
    if cfg.stamp_deferred:
        age = jnp.where(unpack_bits(state.overlay, cfg.k_facts),
                        jnp.uint8(0), age)
    return age


def age_of(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """u8[N, K]: knowledge age in quarter-round ticks, 255 = never/
    unknown — the gated, allocation-honest view for metrics and tests;
    the round kernels use ``mod_age`` + known-gating inline.  Multiply by
    ``STAMP_UNIT`` for (quantized) rounds."""
    known = unpack_bits(state.known, cfg.k_facts)
    return jnp.where(known, mod_age(state, cfg), jnp.uint8(255))


def budgets_of(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """u8[N, K]: remaining transmit budget in quarter-round ticks,
    derived from knowledge age (see the GossipState docstring for the
    invariant)."""
    limit = jnp.uint8(cfg.transmit_limit_q)
    age = age_of(state, cfg)
    return jnp.where(age < limit, limit - age, jnp.uint8(0))


def sending_mask(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """bool[N, K]: facts with remaining transmit budget at alive nodes —
    the per-round packet-selection predicate.  THE place the budget
    derivation is encoded for the round kernels (round_step —
    which the sharded flagship reuses via its ``exchange`` hook —
    and push_round_step); keep in sync with ``budgets_of``."""
    known = unpack_bits(state.known, cfg.k_facts)
    return (known & (mod_age(state, cfg) < jnp.uint8(cfg.transmit_limit_q))
            & state.alive[:, None])


def bump_last_learn(learned_any, learn_round, prev) -> jnp.ndarray:
    """i32 scalar: ``learn_round`` if ``learned_any`` else ``prev``.

    THE one way to maintain GossipState.last_learn — every path that
    writes known/stamp must route through this (the quiet-round gate's
    correctness depends on it; a writer that forgets the bump freezes
    dissemination of its facts once the gate closes)."""
    return jnp.where(learned_any, jnp.asarray(learn_round, jnp.int32), prev)


def clamp_nibbles(nib: jnp.ndarray, round_) -> jnp.ndarray:
    """Re-pin stale 4-bit stamps at q-age ``AGE_PIN_Q`` so derived ages
    can never wrap back under the thresholds (see AGE_PIN_Q/CLAMP_EVERY).
    Applied INLINE by every pass that already streams the stamp plane
    (the merge/push-pull learn passes, the standalone clamp) — zero extra
    HBM traffic on learn rounds.  No ``known`` gate: stamps under cleared
    bits are garbage that is never read, so clamping them is harmless and
    saves the word-plane read the old mod-256 clamp paid."""
    rq = round_q(round_)
    qage = (rq - nib) & jnp.uint8(0xF)
    return jnp.where(qage > jnp.uint8(AGE_PIN_Q),
                     (rq - jnp.uint8(AGE_PIN_Q)) & jnp.uint8(0xF), nib)


def clamp_stamps(stamp: jnp.ndarray, round_, last_clamp, cfg: GossipConfig):
    """Standalone wrap-guard pass, run only when no stamp-streaming pass
    has clamped for ``CLAMP_EVERY`` rounds (quiet/no-learn windows —
    under sustained load the merge learn pass clamps for free every
    round).  Returns ``(stamp, last_clamp)``."""
    def clamp(s):
        if cfg.pack_stamp:
            # per-nibble clamp is independent, so work on the byte
            # halves directly — no K-order interleave
            lo = clamp_nibbles(s & jnp.uint8(0xF), round_)
            hi = clamp_nibbles(s >> 4, round_)
            return lo | (hi << 4)
        return clamp_nibbles(s, round_)

    due = jnp.asarray(round_, jnp.int32) - last_clamp >= CLAMP_EVERY
    stamp = jax.lax.cond(due, clamp, lambda s: s, stamp)
    return stamp, jnp.where(due, jnp.asarray(round_, jnp.int32), last_clamp)


def select_words(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """u32[N, W]: ``pack_bits(sending_mask(...))`` without ever widening
    to K lanes on the packed flavor — the age predicate is evaluated per
    byte column and woven straight into fact words (the same trick the
    pallas select kernel uses).  The recompute path of
    :func:`select_phase` and the ring kernel use this; ``sending_mask``
    remains the bool[N, K] semantic oracle."""
    if cfg.pack_stamp:
        b = state.stamp
        age_ok = nibble_age_pred_words(b & jnp.uint8(0xF), b >> 4,
                                       state.round, cfg.transmit_limit_q)
        if cfg.stamp_deferred:
            # overlay read-through in word space: a learned-since-flush
            # fact's true q-age is 0 < limit_q, whatever its stale
            # nibble says (transmit_limit_q >= 1 by config validation)
            age_ok = age_ok | state.overlay
        alive_words = jnp.where(state.alive[:, None],
                                jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
        return state.known & age_ok & alive_words
    return pack_bits(sending_mask(state, cfg))


# -- rotation addressing -----------------------------------------------------

def rolled_rows(x: jnp.ndarray, shift, doubled=None) -> jnp.ndarray:
    """``y[i] = x[(i + shift) % n]`` along axis 0, without a gather.

    A random-index gather over 1M small rows lowers to a serial loop on
    TPU (measured ~10 ms each); this is one concatenate + one contiguous
    dynamic slice (~2 sequential passes).  ``shift`` may be a traced
    scalar in [0, n).

    ``doubled``: optionally the precomputed ``concatenate([x, x])`` —
    pass it when slicing the SAME array at several shifts (the fanout
    exchange, the indirect-probe rolls) so the doubling materializes
    once by construction rather than by trusting XLA CSE to dedupe
    identical concatenates."""
    n = x.shape[0]
    if doubled is None:
        doubled = jnp.concatenate([x, x], axis=0)
    return jax.lax.dynamic_slice_in_dim(
        doubled, jnp.asarray(shift, jnp.int32), n, axis=0)


def sample_offsets(key: jax.Array, m: int, n: int) -> jnp.ndarray:
    """``m`` random nonzero rotation offsets in [1, n)."""
    return jax.random.randint(key, (m,), 1, n, dtype=jnp.int32)


# -- bit packing helpers -----------------------------------------------------

def _bit_weights() -> jnp.ndarray:
    return (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))


def pack_bits(mask: jnp.ndarray) -> jnp.ndarray:
    """bool[..., K] -> u32[..., K/32]"""
    *lead, k = mask.shape
    m = mask.reshape(*lead, k // 32, 32).astype(jnp.uint32)
    return jnp.sum(m * _bit_weights(), axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray, k: int) -> jnp.ndarray:
    """u32[..., W] -> bool[..., K]"""
    bits = (words[..., :, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    *lead, w, _ = bits.shape
    return bits.reshape(*lead, k).astype(bool)


# -- fact injection ----------------------------------------------------------

def inject_fact(state: GossipState, cfg: GossipConfig, subject, kind,
                incarnation, ltime, origin) -> GossipState:
    """Place one fact into the next ring slot; ``origin`` knows it first.

    Overwriting an old slot retires that fact everywhere (the ring is the
    same bounded-buffer semantics as the reference's dedup cells).  Traceable
    under jit (origin/subject/... may be traced scalars).
    """
    slot = state.next_slot % cfg.k_facts
    word, bit = slot // 32, slot % 32

    # durable death record (see GossipState.tombstone): a K_DEAD fact
    # being retired by this overwrite folds into the tombstone IF its
    # dissemination completed (every alive node knows it); the injected
    # fact clears the record when it is a superseding K_ALIVE
    old_kind = state.facts.kind[slot]
    old_subject = jnp.clip(state.facts.subject[slot], 0)
    known_col = ((state.known[:, word]
                  >> jnp.asarray(bit, jnp.uint32)) & 1).astype(bool)
    covered = jnp.all(known_col | ~state.alive) & jnp.any(state.alive)
    # supersession check (as accusations_pending): a REFUTED death — the
    # subject bumped its incarnation above the declaration's — must not
    # fold, or a live node would be durably recorded dead with no
    # clearing path
    not_superseded = (state.facts.incarnation[slot]
                      >= state.incarnation[old_subject])
    dead_retired = (state.facts.valid[slot] & (old_kind == K_DEAD)
                    & covered & not_superseded)
    tombstone = state.tombstone.at[old_subject].max(dead_retired)
    # overflow accounting (ISSUE 5): overwriting a valid fact whose slot
    # was written fewer than transmit_window_rounds ago drops a fact that
    # was still disseminating — count it (O(1) on K-sized planes)
    clobbered = (state.facts.valid[slot]
                 & ((state.round - state.slot_round[slot])
                    < cfg.transmit_window_rounds))
    overflow = state.overflow + clobbered.astype(jnp.uint32)
    injected_total = state.injected + jnp.uint32(1)
    slot_round = state.slot_round.at[slot].set(state.round)
    is_alive_fact = jnp.asarray(kind, jnp.uint8) == K_ALIVE
    subj_idx = jnp.clip(jnp.asarray(subject, jnp.int32), 0)
    tombstone = tombstone.at[subj_idx].set(
        tombstone[subj_idx] & ~is_alive_fact)

    facts = FactTable(
        subject=state.facts.subject.at[slot].set(jnp.asarray(subject, jnp.int32)),
        kind=state.facts.kind.at[slot].set(jnp.asarray(kind, jnp.uint8)),
        incarnation=state.facts.incarnation.at[slot].set(jnp.asarray(incarnation, jnp.uint32)),
        ltime=state.facts.ltime.at[slot].set(jnp.asarray(ltime, jnp.uint32)),
        valid=state.facts.valid.at[slot].set(True),
    )
    bitmask = (jnp.uint32(1) << bit.astype(jnp.uint32)
               if hasattr(bit, "astype") else jnp.uint32(1 << int(bit)))
    # clear the slot's bit everywhere (fact replaced — the known bit IS the
    # retirement; stale stamps under a cleared bit are never read), then
    # set at origin with a fresh stamp
    known = state.known.at[:, word].set(state.known[:, word] & ~bitmask)
    known = known.at[origin, word].set(known[origin, word] | bitmask)
    rq = round_q(state.round).astype(jnp.int32)
    if cfg.pack_stamp:
        # read-modify-write ONE byte: fact `slot` is nibble slot%2 of
        # byte slot//2 (arithmetic in i32 — traced shifts on u8 promote)
        byte, sh = slot // 2, (slot % 2) * 4
        old = state.stamp[origin, byte].astype(jnp.int32)
        newb = (old & ~(15 << sh)) | (rq << sh)
        stamp = state.stamp.at[origin, byte].set(newb.astype(jnp.uint8))
    else:
        stamp = state.stamp.at[origin, slot].set(round_q(state.round))
    # mirror on the sendable cache (flag-gated at trace time — the
    # escape-hatch config must not pay maintenance): the fresh fact is
    # age-0 sendable at the origin.  The retired slot's stale cache bits
    # are NOT cleared — selection ANDs the cache with `known` (whose
    # retirement clear is mandatory anyway), which is what lets inject
    # skip the second full-plane pass the round-5 mirror paid
    # (accounting.py).
    sendable = state.sendable
    sendable_round = state.sendable_round
    if cfg.use_sendable_cache:
        sendable = sendable.at[origin, word].set(
            sendable[origin, word] | bitmask)
    else:
        # learned without mirroring: a later flag-on run must not trust
        # this plane (mixed-flag hygiene)
        sendable_round = jnp.asarray(-1, jnp.int32)
    return state._replace(facts=facts, known=known,
                          stamp=stamp, next_slot=state.next_slot + 1,
                          tombstone=tombstone,
                          sendable=sendable, sendable_round=sendable_round,
                          slot_round=slot_round, overflow=overflow,
                          injected=injected_total,
                          last_learn=bump_last_learn(True, state.round,
                                                     state.last_learn))


def inject_facts_batch(state: GossipState, cfg: GossipConfig, subjects,
                       kind: int, incarnations, ltimes, origins,
                       active) -> GossipState:
    """Inject up to ``M = len(subjects)`` facts in ONE pass.

    ``active`` (bool[M]) must be a *prefix* mask (all True entries first) —
    active facts take consecutive ring slots starting at ``next_slot``.
    Inactive entries are dropped via out-of-bounds scatter indices.

    Equivalent to ``M`` sequential ``inject_fact`` calls.  With the stamp
    plane the whole batch is two bounded scatters (known-bit set at
    origins, stamp at origins) plus one pass over the N×W word plane for
    retirement — the N×K plane is NOT rewritten (the round-1 sequential
    form moved ~130 MB × M per phase through HBM; the round-2 batched form
    still rewrote the 64 MB age plane once per phase for retirement).
    """
    n, k = cfg.n, cfg.k_facts
    m = subjects.shape[0]
    if m > k:
        # consecutive slots would alias modulo the ring and the scatter-add
        # OR trick would corrupt the known bitmap
        raise ValueError(f"batch of {m} facts exceeds ring capacity {k}")
    subjects = jnp.asarray(subjects, jnp.int32)
    origins = jnp.asarray(origins, jnp.int32)

    slots = (state.next_slot + jnp.arange(m, dtype=jnp.int32)) % k
    # OOB index (k / n) + mode='drop' skips the write entirely
    wslots = jnp.where(active, slots, k)
    worigins = jnp.where(active, origins, n)

    # durable death record (see GossipState.tombstone): retiring,
    # fully-disseminated K_DEAD facts fold in; K_ALIVE injections clear
    # their subjects.  Per retired slot, "covered" = every alive node
    # holds the known bit (m columns of the packed plane).  Skip-gated
    # on an M-sized predicate: the coverage gather + alive reads only
    # run when a retiring slot actually holds a live death declaration —
    # under sustained USER-EVENT load the ring recycles events, the gate
    # stays closed, and the fold's ~11 MB/round at 1M is not paid
    # (accounting.py); detection bursts open it.
    r_slots = jnp.clip(slots, 0, k - 1)
    r_subj = jnp.clip(state.facts.subject[r_slots], 0)
    maybe_dead = (state.facts.valid[r_slots]
                  & (state.facts.kind[r_slots] == K_DEAD) & active)

    def fold(ts):
        r_words = r_slots // 32
        r_bits = (r_slots % 32).astype(jnp.uint32)
        cols = ((state.known[:, r_words] >> r_bits[None, :]) & 1
                ).astype(bool)
        covered = (jnp.all(cols | ~state.alive[:, None], axis=0)
                   & jnp.any(state.alive))                    # bool[M]
        # supersession check (see inject_fact): refuted deaths must not
        # fold
        not_superseded = (state.facts.incarnation[r_slots]
                          >= state.incarnation[r_subj])
        dead_retired = maybe_dead & covered & not_superseded
        old_subjects = jnp.where(dead_retired, r_subj, n)
        return ts.at[old_subjects].max(True, mode="drop")

    tombstone = jax.lax.cond(jnp.any(maybe_dead), fold,
                             lambda ts: ts, state.tombstone)

    # overflow accounting (ISSUE 5): active entries overwriting a valid
    # fact whose slot was written inside the transmit window drop a
    # still-disseminating fact.  O(M) gathers on K-sized arrays — no
    # N-plane traffic, so the sustained-regime HBM model is untouched.
    # Chunked storm injections land in the same round, so a burst past
    # ring capacity counts every still-live slot it clobbers.
    clobbered = (state.facts.valid[r_slots] & active
                 & ((state.round - state.slot_round[r_slots])
                    < cfg.transmit_window_rounds))
    overflow = state.overflow + jnp.sum(clobbered).astype(jnp.uint32)
    injected_total = state.injected + jnp.sum(active).astype(jnp.uint32)
    slot_round = state.slot_round.at[wslots].set(state.round, mode="drop")

    if kind == K_ALIVE:
        tombstone = tombstone.at[
            jnp.where(active, jnp.clip(subjects, 0), n)].set(
            False, mode="drop")

    facts = FactTable(
        subject=state.facts.subject.at[wslots].set(subjects, mode="drop"),
        kind=state.facts.kind.at[wslots].set(jnp.uint8(kind), mode="drop"),
        incarnation=state.facts.incarnation.at[wslots].set(
            jnp.asarray(incarnations, jnp.uint32), mode="drop"),
        ltime=state.facts.ltime.at[wslots].set(
            jnp.asarray(ltimes, jnp.uint32), mode="drop"),
        valid=state.facts.valid.at[wslots].set(True, mode="drop"),
    )

    # bool[K]: ring slots overwritten this batch (their old fact retires)
    written = jnp.zeros((k,), bool).at[wslots].set(True, mode="drop")
    clear_words = pack_bits(written)                          # u32[W]

    # known: clear retired slots everywhere, then set each fact's bit at its
    # origin.  Bits are distinct within the batch and just cleared, so a
    # scatter-add is an OR.
    known = state.known & ~clear_words[None, :]
    words = wslots // 32
    bitmasks = jnp.where(active,
                         jnp.uint32(1) << (wslots % 32).astype(jnp.uint32),
                         jnp.uint32(0))
    known = known.at[worigins, jnp.where(active, words, 0)].add(
        bitmasks, mode="drop")

    rq = round_q(state.round).astype(jnp.int32)
    if cfg.pack_stamp:
        # nibble scatter with duplicate-byte resolution: consecutive
        # slots mean two batch entries can share a (origin, byte) pair —
        # one per nibble (same origin, slots 2j and 2j+1).  A scatter-set
        # with duplicate indices is order-undefined, so each entry
        # computes the byte's FINAL value (folding every same-byte
        # partner over the gathered old byte, an M×M trace-time-tiny
        # reduction) — duplicates then write identical bytes and any
        # winner is correct.
        cols = cfg.stamp_cols
        b = wslots // 2                                       # i32[M]
        sh = (wslots % 2) * 4                                 # i32[M]
        gb = state.stamp[jnp.clip(worigins, 0, n - 1),
                         jnp.clip(b, 0, cols - 1)].astype(jnp.int32)
        same = ((worigins[:, None] == worigins[None, :])
                & (b[:, None] == b[None, :])
                & active[:, None] & active[None, :])          # bool[M, M]
        clear = jnp.sum(jnp.where(same, 15 << sh[None, :], 0), axis=1)
        val = jnp.sum(jnp.where(same, rq << sh[None, :], 0), axis=1)
        newb = ((gb & ~clear) | val).astype(jnp.uint8)
        stamp = state.stamp.at[worigins, jnp.where(active, b, cols)].set(
            newb, mode="drop")
    else:
        stamp = state.stamp.at[worigins, wslots].set(
            round_q(state.round), mode="drop")

    # sendable cache mirror (see inject_fact; flag-gated at trace time):
    # age-0 bits at the origins only — retired slots' stale cache bits
    # are masked by `known` at selection, so the full-plane clear the
    # round-5 mirror paid is gone.  Because stale bits may remain, the
    # scatter must be an OR, not an add: gather the old words, fold every
    # same-(origin, word) partner's bit in (distinct slots = distinct
    # bits, so the sum IS the OR), and set identical finals (duplicate
    # set indices with equal payloads are well-defined).
    sendable = state.sendable
    sendable_round = state.sendable_round
    if cfg.use_sendable_cache:
        gw = sendable[jnp.clip(worigins, 0, n - 1),
                      jnp.clip(words, 0, cfg.words - 1)]
        same_w = ((worigins[:, None] == worigins[None, :])
                  & (words[:, None] == words[None, :])
                  & active[:, None] & active[None, :])
        orv = jnp.sum(jnp.where(same_w, bitmasks[None, :],
                                jnp.uint32(0)), axis=1, dtype=jnp.uint32)
        sendable = sendable.at[worigins, jnp.where(active, words, 0)].set(
            gw | orv, mode="drop")
    else:
        sendable_round = jnp.asarray(-1, jnp.int32)

    return state._replace(facts=facts, known=known, stamp=stamp,
                          tombstone=tombstone,
                          sendable=sendable, sendable_round=sendable_round,
                          slot_round=slot_round, overflow=overflow,
                          injected=injected_total,
                          next_slot=state.next_slot
                          + jnp.sum(active).astype(jnp.int32),
                          last_learn=bump_last_learn(
                              jnp.any(active), state.round,
                              state.last_learn))


#: below this, a flat top_k over all n scores is cheap; above it, top_k's
#: full sort dominates the round (measured 1.9 ms per call at 1M — three
#: calls per swim round) and the two-level groupwise pick wins (0.7 ms)
_PICK_FLAT_MAX = 1 << 16
#: number of strided groups for the two-level pick (top_k runs over this
#: many group maxima)
_PICK_GROUPS = 4096


def pick_bounded(candidates: jnp.ndarray, max_events: int, key: jax.Array):
    """Bounded selection: choose ≤``max_events`` of the candidate nodes
    (bool[N]) by randomized scoring.

    Returns ``(chosen bool[N], subjects i32[M], active bool[M])``; the
    active entries are a contiguous prefix — exactly the
    ``inject_facts_batch`` contract (real candidates score > 0, others 0,
    and selection sorts descending).

    Small n: one flat randomized top_k (unbiased).  Large n: two-level —
    the index space is split into ``_PICK_GROUPS`` groups, each group
    elects its max-score candidate in one elementwise pass, and top_k
    runs over only the G group maxima.  At most one winner per group per
    round is a selection bias; to keep any FIXED candidate set from
    being degenerate, the grouping LAYOUT alternates per round (keyed off
    the PRNG): *strided* groups (group j = indices ≡ j mod G — spreads
    contiguous id ranges: range partitions, rack failures) or
    *contiguous blocks* (group j = indices j·rows..(j+1)·rows — spreads
    arithmetic progressions: a set colliding mod G is spaced ≥ G apart,
    so blocks of rows < G hold at most one each).  No set collides under
    BOTH layouts, so an adversarial set drains at ≥ half the ideal rate
    (quantified in tests/test_device_plane.py::test_pick_bounded_adversarial_drain;
    analysis in DESIGN.md).  Un-picked candidates simply remain
    candidates for the next round (the max_events bound already defers
    extras).  Both layouts are pure reshapes — no gathers — preserving
    the win over the full 1M-element sort that made the flat top_k the
    single most expensive op in the swim round.
    """
    def topk_padded(scores: jnp.ndarray):
        # top_k requires k <= the axis size; clamp and pad the tail with
        # zero scores (inactive by the `vals > 0` predicate below)
        kk = min(max_events, scores.shape[0])
        vals, idx = jax.lax.top_k(scores, kk)
        if kk < max_events:
            vals = jnp.pad(vals, (0, max_events - kk))
            idx = jnp.pad(idx, (0, max_events - kk))
        return vals, idx

    n = candidates.shape[0]
    k_score, k_layout = jax.random.split(key)
    score = candidates.astype(jnp.float32) * (
        1.0 + jax.random.uniform(k_score, (n,)))
    if n <= _PICK_FLAT_MAX:
        vals, idx = topk_padded(score)
        active = vals > 0.0
        subjects = idx.astype(jnp.int32)
    else:
        g = _PICK_GROUPS
        # the blocks-vs-strided complementarity proof needs rows <= g
        # (a mod-g-colliding set is spaced g apart, so blocks of rows <= g
        # hold at most one member each); above n = g^2 (~16.7M) grow the
        # group count to the next power of two >= sqrt(n).  n is static
        # under jit, so this is trace-time Python.
        while (n + g - 1) // g > g:
            g *= 2
        rows = (n + g - 1) // g
        padded = score if rows * g == n else jnp.pad(score,
                                                     (0, rows * g - n))

        def strided(p):
            s2 = p.reshape(rows, g)     # column j = indices ≡ j mod g
            winner = (jnp.argmax(s2, axis=0).astype(jnp.int32) * g
                      + jnp.arange(g, dtype=jnp.int32))
            return jnp.max(s2, axis=0), winner

        def blocks(p):
            s2 = p.reshape(g, rows)     # row j = indices j*rows..+rows
            winner = (jnp.arange(g, dtype=jnp.int32) * rows
                      + jnp.argmax(s2, axis=1).astype(jnp.int32))
            return jnp.max(s2, axis=1), winner

        grp_max, grp_winner = jax.lax.cond(
            jax.random.bernoulli(k_layout), strided, blocks, padded)
        # at most one winner per group, so only min(max_events, G) picks
        # are possible; the tail comes back inactive
        vals, cols = topk_padded(grp_max)
        active = vals > 0.0
        subjects = grp_winner[cols]
    chosen = jnp.zeros((n,), bool).at[
        jnp.where(active, subjects, n)].set(True, mode="drop")
    return chosen, subjects, active


# -- the gossip round kernel -------------------------------------------------

def pallas_dispatch_mode(cfg: GossipConfig,
                         n_devices: int = 0) -> Tuple[str, str]:
    """THE pallas dispatch decision, pure (no recording — the
    profiler's path labeling uses it too): ``("", reason)`` for XLA,
    ``("kernels", "")`` for the PR-3 standalone family, or
    ``("fused", "")`` for the cache-maintaining fused family — the only
    one that runs under shard_map on the sharded flagship path.
    ``n_devices=0`` means unsharded (no mesh); ``>=1`` means sharded
    over that many chips (a 1-device mesh is still the shard_map
    path)."""
    if not cfg.use_pallas:
        return "", "use_pallas off"
    from serf_tpu.ops import round_kernels
    if not cfg.fused_kernels:
        if cfg.stamp_deferred:
            # the PR-3 standalone family predates the overlay plane
            # (its merge writes stamps per-round and its select never
            # reads the overlay) — deferred configs take the fused
            # family or the XLA path, never half-deferred kernels
            return "", ("standalone kernels do not maintain the "
                        "deferred-stamp overlay; use fused_kernels")
        if n_devices == 0 and round_kernels.pallas_ok(cfg.n, cfg.k_facts):
            return "kernels", ""
        return "", ("standalone kernels are single-device; use "
                    "fused_kernels for the sharded path"
                    if n_devices else "pallas_ok rejected shape")
    d = max(1, n_devices)
    if cfg.n % d != 0:
        return "", f"n % devices != 0 (n={cfg.n}, devices={d})"
    ok, reason = round_kernels.fused_ok(cfg.n // d, cfg.k_facts,
                                        cfg.stamp_cols,
                                        deferred=cfg.stamp_deferred)
    return ("fused", "") if ok else ("", reason)


def _pallas_mode(cfg: GossipConfig, mesh=None, op: str = "round_step",
                 record: bool = True) -> str:
    """Trace-time dispatch wrapper around :func:`pallas_dispatch_mode`.
    A rejection of a ``use_pallas`` config is LOUD when ``record``: a
    ``pallas-fallback`` flight event with the reason plus a
    ``serf.pallas.fused_fallback`` counter bump — once per round trace
    (only the selection phase records; the merge passes
    ``record=False``)."""
    n_devices = 0
    if mesh is not None:
        from serf_tpu.parallel.mesh import NODE_AXIS
        n_devices = mesh.shape[NODE_AXIS]
    mode, reason = pallas_dispatch_mode(cfg, n_devices)
    if mode or not cfg.use_pallas:
        return mode
    if record:
        from serf_tpu import obs
        from serf_tpu.utils import metrics
        obs.record("pallas-fallback", op=op, n=cfg.n, k=cfg.k_facts,
                   reason=reason)
        metrics.incr("serf.pallas.fused_fallback", 1, {"op": op})
    return ""


def select_phase(state: GossipState, cfg: GossipConfig,
                 mesh=None) -> jnp.ndarray:
    """Phase 1 — packet selection: u32[N, W] of sending bits.

    Cached path: ``sendable & known`` under the alive mask — the AND
    with ``known`` is what masks stale cache bits for retired ring slots
    (see GossipState.sendable_round), trading an N×W read here for the
    inject path's second full-plane retirement pass.  Falls back to the
    stamp-plane recompute when the cache is stale.

    Pallas flavors: the FUSED family honors the cache exactly like the
    XLA path (the fused merge maintains it, so the valid branch is a
    word-plane-only kernel — no stamp read; this is the full-plane pass
    the fusion removes); the standalone family never trusts the cache
    (its merge invalidates) and always runs the stamp recompute
    kernel."""
    mode = _pallas_mode(cfg, mesh)
    if mode:
        from serf_tpu.ops import round_kernels

        def recompute(s):
            if cfg.stamp_deferred:
                # stale-cache recompute on the deferred path must read
                # through the overlay (mid-cohort learns are not in the
                # stamp plane yet); the stamp-only kernel can't — take
                # the overlay-aware XLA recompute.  Rare by design: the
                # deferred merge keeps the cache valid mid-cohort.
                return select_words(s, cfg)
            return round_kernels.select_packets(
                s.stamp, s.known, s.alive[:, None].astype(jnp.uint8),
                cfg.transmit_limit_q, s.round, packed=cfg.pack_stamp,
                k_facts=cfg.k_facts, mesh=mesh)

        if mode == "fused" and cfg.use_sendable_cache:
            return jax.lax.cond(
                state.sendable_round == state.round,
                lambda s: round_kernels.fused_select_cached(
                    s.sendable, s.known,
                    s.alive[:, None].astype(jnp.uint8),
                    k_facts=cfg.k_facts, stamp_cols=cfg.stamp_cols,
                    mesh=mesh),
                recompute, state)
        return recompute(state)
    if cfg.use_sendable_cache:
        return jax.lax.cond(
            state.sendable_round == state.round,
            lambda s: jnp.where(s.alive[:, None],
                                s.sendable & s.known, jnp.uint32(0)),
            lambda s: select_words(s, cfg),
            state)
    return select_words(state, cfg)


def exchange_phase(packets: jnp.ndarray, cfg: GossipConfig,
                   key: jax.Array, group=None,
                   drop_rate=None, eff_fanout=None) -> jnp.ndarray:
    """Phase 3 — pull-exchange: each node ORs ``fanout`` peers' packets.

    Rotation mode: fanout random rotations shared by all nodes — peer
    reads are contiguous slices, no gather (GossipConfig.peer_sampling);
    the doubled array is hoisted across the fanout slices, ONE
    materialization by construction (the byte model's "concat once"
    term, accounting.py).  ``group`` masks cross-partition flow.

    ``drop_rate`` (optional f32 scalar, may be traced) is the chaos
    plane's per-round delivery mask (serf_tpu.faults.device): each
    (receiver, peer) exchange is independently lost with that
    probability — the device analog of per-edge UDP loss.  None (the
    default) compiles the fault path out entirely.

    ``eff_fanout`` (optional i32 scalar, may be traced) is the adaptive
    control plane's effective fan-out (serf_tpu.control.device):
    contributions ``f >= eff_fanout`` are masked out.  The static
    ``cfg.fanout`` stays the shape bound and the RNG stream is
    identical for every value, so the controller changing fan-out never
    perturbs the peer sampling of the legs it keeps.  None (the
    default) compiles the mask out entirely."""
    n = packets.shape[0]
    if drop_rate is not None:
        key, k_drop = jax.random.split(key)
    if cfg.peer_sampling == "rotation":
        offs = sample_offsets(key, cfg.fanout, n)
        doubled = jnp.concatenate([packets, packets], axis=0)
        dgroup = (jnp.concatenate([group, group], axis=0)
                  if group is not None else None)
        lost = (jax.random.bernoulli(k_drop, drop_rate, (cfg.fanout, n))
                if drop_rate is not None else None)
        incoming = jnp.zeros_like(packets)
        for f in range(cfg.fanout):
            contrib = rolled_rows(packets, offs[f], doubled=doubled)
            if group is not None:
                allowed = rolled_rows(group, offs[f],
                                      doubled=dgroup) == group
                contrib = jnp.where(allowed[:, None], contrib,
                                    jnp.uint32(0))
            if lost is not None:
                contrib = jnp.where(lost[f][:, None], jnp.uint32(0),
                                    contrib)
            if eff_fanout is not None:
                contrib = jnp.where(
                    jnp.asarray(f, jnp.int32) < eff_fanout, contrib,
                    jnp.uint32(0))
            incoming = incoming | contrib
        return incoming
    srcs = jax.random.randint(key, (n, cfg.fanout), 0, n)
    gathered = packets[srcs]                          # u32[N, F, W]
    if group is not None:
        allowed = (group[srcs] == group[:, None])     # bool[N, F]
        gathered = jnp.where(allowed[:, :, None], gathered,
                             jnp.uint32(0))
    if drop_rate is not None:
        lost = jax.random.bernoulli(k_drop, drop_rate, (n, cfg.fanout))
        gathered = jnp.where(lost[:, :, None], jnp.uint32(0), gathered)
    if eff_fanout is not None:
        fmask = jnp.arange(cfg.fanout, dtype=jnp.int32) < eff_fanout
        gathered = jnp.where(fmask[None, :, None], gathered,
                             jnp.uint32(0))
    return jax.lax.reduce(gathered, jnp.uint32(0),
                          jnp.bitwise_or, (1,))       # u32[N, W]


def learn_stamp_pass(stamp: jnp.ndarray, known: jnp.ndarray,
                     new_words: jnp.ndarray, next_round,
                     cfg: GossipConfig, fallback_sendable: jnp.ndarray):
    """THE stamp learn pass: one streaming read+write of the stamp plane
    that (a) re-pins wrap-stale stamps (clamp_nibbles — free while the
    plane streams), (b) stamps newly learned facts (``new_words``) with
    ``next_round``'s quarter, and (c) recomputes the sendable cache for
    ``next_round`` in the same fusion (or invalidates it when the cache
    flag is off).  Packed flavor works entirely in BYTE space — no
    K-order interleave (a layout shuffle XLA materializes; it cost ~1.5×
    on the CPU round) and no known-plane unpack (the cache is
    ``known & woven-age-words`` directly).

    Returns ``(stamp', sendable', sendable_round')``.  The single
    definition :func:`merge_phase` applies for EVERY exchange schedule —
    the sharded flagship swaps only ``round_step``'s exchange leg, so
    all schedules share this one copy of the arithmetic and stay
    bit-identical by construction (``antientropy.push_pull_round`` has a
    reduced stamp-only variant with its own cache semantics)."""
    k = cfg.k_facts
    rq = round_q(next_round)
    limit_q = jnp.uint8(cfg.transmit_limit_q)
    if cfg.pack_stamp:
        stamp2, lo, hi = clamp_learn_bytes(stamp, new_words, next_round, k)
        if cfg.use_sendable_cache:
            age_ok = nibble_age_pred_words(lo, hi, next_round, limit_q)
            return (stamp2, known & age_ok,
                    jnp.asarray(next_round, jnp.int32))
        return stamp2, fallback_sendable, jnp.asarray(-1, jnp.int32)
    nib = clamp_nibbles(stamp, next_round)
    new_mask = unpack_bits(new_words, k)              # bool[N, K]
    stamp2 = jnp.where(new_mask, rq, nib)
    if cfg.use_sendable_cache:
        kb = unpack_bits(known, k)
        q_next = (rq - stamp2) & jnp.uint8(0xF)
        return (stamp2, pack_bits(kb & (q_next < limit_q)),
                jnp.asarray(next_round, jnp.int32))
    # learned without mirroring: mixed-flag hygiene
    return stamp2, fallback_sendable, jnp.asarray(-1, jnp.int32)


def flush_stamp_pass(stamp: jnp.ndarray, known: jnp.ndarray,
                     new_words: jnp.ndarray, overlay: jnp.ndarray,
                     next_round, cfg: GossipConfig,
                     fallback_sendable: jnp.ndarray):
    """THE cohort flush (quarter-deferred flavor of
    :func:`learn_stamp_pass`): the one stamp-plane streaming pass of a
    ``stamp_flush_unit``-round cohort.  In the same fusion it (a)
    re-pins wrap-stale nibbles (clamp), (b) writes every pending
    overlay cell with the COHORT quarter ``round_q(next_round - 1)`` —
    exact, because a cohort never spans a quarter boundary (config
    validation: the unit divides STAMP_UNIT), so every mid-cohort learn
    happened in that quarter — (c) stamps THIS merge's fresh learns
    (``new_words``) with ``round_q(next_round)`` (fresh learns at a
    flush merge go to the stamp plane directly, never the overlay;
    ``new_words`` wins where a stale overlay bit survives slot
    recycling), and (d) recomputes the sendable cache for
    ``next_round`` from the final nibbles.  The caller clears the
    overlay and sets ``last_flush = next_round``.

    Returns ``(stamp', sendable', sendable_round')`` — the
    :func:`learn_stamp_pass` contract."""
    k = cfg.k_facts
    rq = round_q(next_round)
    rq_prev = round_q(jnp.asarray(next_round, jnp.int32) - 1)
    limit_q = jnp.uint8(cfg.transmit_limit_q)
    if cfg.pack_stamp:
        lo = clamp_nibbles(stamp & jnp.uint8(0xF), next_round)
        hi = clamp_nibbles(stamp >> 4, next_round)
        o_lo, o_hi = learn_pairs_words(overlay, k)
        lo = jnp.where(o_lo, rq_prev, lo)
        hi = jnp.where(o_hi, rq_prev, hi)
        n_lo, n_hi = learn_pairs_words(new_words, k)
        lo = jnp.where(n_lo, rq, lo)
        hi = jnp.where(n_hi, rq, hi)
        stamp2 = lo | (hi << 4)
        if cfg.use_sendable_cache:
            age_ok = nibble_age_pred_words(lo, hi, next_round, limit_q)
            return (stamp2, known & age_ok,
                    jnp.asarray(next_round, jnp.int32))
        return stamp2, fallback_sendable, jnp.asarray(-1, jnp.int32)
    nib = clamp_nibbles(stamp, next_round)
    nib = jnp.where(unpack_bits(overlay, k), rq_prev, nib)
    nib = jnp.where(unpack_bits(new_words, k), rq, nib)
    if cfg.use_sendable_cache:
        kb = unpack_bits(known, k)
        q_next = (rq - nib) & jnp.uint8(0xF)
        return (nib, pack_bits(kb & (q_next < limit_q)),
                jnp.asarray(next_round, jnp.int32))
    return nib, fallback_sendable, jnp.asarray(-1, jnp.int32)


def merge_phase(state: GossipState, incoming: jnp.ndarray,
                cfg: GossipConfig, mesh=None,
                stamp_unit=None) -> GossipState:
    """Phases 4+5 — Lamport merge + the stamp learn pass.

    Learn facts we did not know (dead learn nothing), then the round's
    only stamp-plane write: stamp newly learned facts with the
    post-increment round's quarter — their derived q-age is 0 at the
    next round's selection; everyone else's age advances for free
    because ``round`` advanced.  Gated on ``learned_any``: with zero
    learns the where is a bit-exact identity, and skipping it saves the
    round's biggest single pass (stamp R+W, 64 MB at 1M×64 packed)
    during the fully-disseminated window the gossip gate hasn't closed
    yet (see serf_tpu/models/accounting.py).  While the stamp plane is
    streaming through this pass anyway, two more jobs ride the same
    fusion for free: the wrap clamp (``clamp_nibbles`` — so the
    standalone clamp pass never fires under sustained load) and the
    sendable-cache recompute for round+1 (expiry transitions included —
    the only place the cache's validity round advances).

    The FUSED pallas flavor (``ops.fused_merge``) carries all three jobs
    in one authored kernel pass and emits per-block learn flags; its
    outputs are gated through the SAME ``learned_any`` cond as the XLA
    path, so both paths are bit-exact on every leaf (stamp clamp timing
    and cache validity included).  The standalone flavor keeps its PR-3
    semantics: clamp every active round, cache invalidated.

    DEFERRED flavor (``cfg.stamp_deferred``, PR-18): the stamp-plane
    write is amortized to once per ``stamp_flush_unit``-round cohort —
    mid-cohort merges are word-plane ORs only (known/overlay/sendable),
    and the cohort's one flush pass (:func:`flush_stamp_pass` /
    ``ops.fused_flush``) retires the overlay into the stamp plane.
    ``stamp_unit`` (optional i32 scalar, may be traced) overrides the
    config's static unit — the adaptive control plane's STAMP_UNIT knob
    rides this; only ever passed on deferred configs.

    Does NOT increment ``state.round`` (the caller owns the round
    counter and the standalone clamp)."""
    k = cfg.k_facts
    mode = _pallas_mode(cfg, mesh, record=False)
    if cfg.stamp_deferred:
        return _merge_phase_deferred(state, incoming, cfg, mode, mesh,
                                     stamp_unit)
    if mode == "fused":
        from serf_tpu.ops import round_kernels
        alive_u8 = state.alive[:, None].astype(jnp.uint8)
        known, stamp2, sendable2, flags = round_kernels.fused_merge(
            state.known, incoming, alive_u8, state.stamp,
            state.round + 1, limit_q=cfg.transmit_limit_q,
            packed=cfg.pack_stamp, k_facts=k,
            with_cache=cfg.use_sendable_cache, mesh=mesh)
        learned_any = jnp.any(flags != 0)
        r1 = jnp.asarray(state.round + 1, jnp.int32)

        def learned(_):
            if cfg.use_sendable_cache:
                return stamp2, sendable2, r1, r1
            # learned without mirroring: mixed-flag hygiene (same as
            # learn_stamp_pass's cache-off branch)
            return stamp2, state.sendable, jnp.asarray(-1, jnp.int32), r1

        # identical gating to the XLA path below: when nothing is
        # learned the kernel's stamp/cache outputs are DISCARDED (the
        # clamp must not advance last_clamp off-schedule) — known is
        # bit-exact either way (no learns => known' == known)
        stamp, sendable, sendable_round, last_clamp = jax.lax.cond(
            learned_any, learned,
            lambda _: (state.stamp, state.sendable,
                       state.sendable_round, state.last_clamp), None)
    elif mode == "kernels":
        from serf_tpu.ops import round_kernels
        alive_u8 = state.alive[:, None].astype(jnp.uint8)
        # standalone kernel: learn + stamp + inline clamp.  "learned
        # anything" is definitional (output vs input known) so it can
        # never desync from the kernel's learn semantics.
        known, stamp = round_kernels.merge_incoming(
            state.known, incoming, alive_u8, state.stamp,
            state.round + 1, packed=cfg.pack_stamp, k_facts=k)
        learned_any = jnp.any(known != state.known)
        # the kernel learns without maintaining the cache — a later
        # cached selection on this state would miss those learns, so
        # invalidate (this path always selects from stamps)
        sendable = state.sendable
        sendable_round = jnp.asarray(-1, jnp.int32)
        last_clamp = jnp.asarray(state.round + 1, jnp.int32)
    else:
        alive_col = state.alive[:, None]
        new_words = incoming & ~state.known & jnp.where(
            alive_col, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
        known = state.known | new_words
        learned_any = jnp.any(new_words != 0)

        def stamp_learns(_):
            stamp2, send2, sr2 = learn_stamp_pass(
                state.stamp, known, new_words, state.round + 1, cfg,
                state.sendable)
            return (stamp2, send2, sr2,
                    jnp.asarray(state.round + 1, jnp.int32))

        stamp, sendable, sendable_round, last_clamp = jax.lax.cond(
            learned_any, stamp_learns,
            lambda _: (state.stamp, state.sendable,
                       state.sendable_round, state.last_clamp), None)
    last_learn = bump_last_learn(learned_any, state.round + 1,
                                 state.last_learn)
    return state._replace(known=known, stamp=stamp, last_learn=last_learn,
                          sendable=sendable, sendable_round=sendable_round,
                          last_clamp=last_clamp)


def _merge_phase_deferred(state: GossipState, incoming: jnp.ndarray,
                          cfg: GossipConfig, mode: str, mesh,
                          stamp_unit) -> GossipState:
    """:func:`merge_phase`, deferred-stamp flavor (``stamp_flush_unit``
    > 1).  The word-plane merge (learn bits into known/overlay/sendable)
    runs EVERY active round; the stamp plane is only touched by the
    once-per-cohort flush:

    - ``flush_due``: the post-increment round is a cohort boundary
      (``(round+1) % unit == 0`` — units divide STAMP_UNIT by config
      validation, so a cohort never spans a stamp quarter and every
      pending overlay cell shares the quarter ``round_q(flush-1)``).
    - ``do_flush = flush_due & (learned_any | pending)``: a boundary
      with nothing pending and nothing learned skips the pass entirely
      (the deferred analog of the per-round path's ``learned_any``
      gate), where ``pending = last_learn > last_flush`` — mid-cohort
      learns that still owe a stamp write.

    Mid-cohort the sendable cache stays VALID: the defer branch ORs the
    learn bits in (their overlay-derived q-age is 0 < limit) and no
    expiry transition can occur (ages only change at quarter
    boundaries, which are always cohort boundaries) — so the validity
    round advances, EXCEPT across a skipped boundary (``~flush_due``
    gate), where a quarter crossing may expire cached bits and the
    cache must go stale for the readers' recompute to see it.

    The word-plane ORs stay XLA on every dispatch mode — they fuse
    bandwidth-optimally and there is no stamp pass to ride; the fused
    family contributes its streaming flush kernel (``ops.fused_flush``)
    under the same ``do_flush`` cond, so both paths are bit-exact on
    every leaf."""
    nxt = jnp.asarray(state.round + 1, jnp.int32)
    unit = jnp.asarray(
        cfg.stamp_flush_unit if stamp_unit is None else stamp_unit,
        jnp.int32)
    alive_col = state.alive[:, None]
    new_words = incoming & ~state.known & jnp.where(
        alive_col, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    known = state.known | new_words
    learned_any = jnp.any(new_words != 0)

    flush_due = (nxt % unit) == 0
    pending = state.last_learn > state.last_flush
    do_flush = flush_due & (learned_any | pending)

    def flush(_):
        if mode == "fused":
            from serf_tpu.ops import round_kernels
            stamp2, send2 = round_kernels.fused_flush(
                known, new_words, state.overlay, state.stamp, nxt,
                limit_q=cfg.transmit_limit_q, packed=cfg.pack_stamp,
                k_facts=cfg.k_facts, with_cache=cfg.use_sendable_cache,
                mesh=mesh)
            if not cfg.use_sendable_cache:
                send2, sr2 = state.sendable, jnp.asarray(-1, jnp.int32)
            else:
                sr2 = nxt
        else:
            stamp2, send2, sr2 = flush_stamp_pass(
                state.stamp, known, new_words, state.overlay, nxt, cfg,
                state.sendable)
        return (stamp2, jnp.zeros_like(state.overlay), send2,
                jnp.asarray(sr2, jnp.int32), nxt, nxt)

    def defer(_):
        sr2 = jnp.where(
            (state.sendable_round == state.round) & ~flush_due,
            nxt, state.sendable_round)
        return (state.stamp, state.overlay | new_words,
                state.sendable | new_words, sr2,
                state.last_clamp, state.last_flush)

    (stamp, overlay, sendable, sendable_round, last_clamp,
     last_flush) = jax.lax.cond(do_flush, flush, defer, None)
    last_learn = bump_last_learn(learned_any, nxt, state.last_learn)
    return state._replace(known=known, stamp=stamp, overlay=overlay,
                          last_learn=last_learn, sendable=sendable,
                          sendable_round=sendable_round,
                          last_clamp=last_clamp, last_flush=last_flush)


def round_step(state: GossipState, cfg: GossipConfig,
               key: jax.Array, group=None, drop_rate=None,
               exchange=None, mesh=None, eff_fanout=None,
               collect_propagation: bool = False,
               stamp_unit=None):
    """One gossip round: select packets, pull-exchange, Lamport-merge
    (the :func:`select_phase`/:func:`exchange_phase`/:func:`merge_phase`
    composition — the profiler jits the same phases in isolation,
    serf_tpu/obs/profile.py).

    Vectorized translation of the reference hot path: `get_broadcasts` drain
    (budget decrement) + `SerfDelegate::broadcast_messages` piggybacking +
    per-receiver `handle_*` first-sight rebroadcast decision
    (reference delegate.rs:317-384, base.rs:783-813).

    ``group`` (optional i32[N]) is the partition mask: packets only flow
    between nodes in the same group — the device analog of the reference's
    block-diagonal adjacency partition (SURVEY.md §7 stage 6).

    Skip-gated on ``round - last_learn < transmit_window_rounds`` (the
    q-window's round-unit upper bound): past that, every knower's
    derived q-age is >= transmit_limit_q, the sending set is provably
    empty, and the whole select/exchange/merge is a bit-exact identity — a fully quiescent cluster (serf with an empty broadcast
    queue) pays only the round increment and the amortized clamp.  A new
    injection or merge bumps ``last_learn`` and re-opens the gate.

    ``exchange`` (optional) swaps the exchange leg for a drop-in with
    the same ``(packets, cfg, key, group=, drop_rate=)`` contract — THE
    hook the sharded flagship uses (``parallel.ring.exchange_sharded``
    runs the leg under shard_map with an explicit ICI schedule).  One
    copy of everything around the leg is what keeps the sharded round
    bit-exact with this one by construction.

    ``eff_fanout`` (optional i32 scalar, may be traced) is the adaptive
    control plane's effective fan-out (serf_tpu.control): forwarded to
    the exchange leg, which masks contributions ``f >= eff_fanout`` out
    — the kwarg is only passed when live, so custom exchange hooks that
    predate it keep working.

    ``mesh`` (optional) tells the select/merge phases they are running
    on node-sharded state so the FUSED pallas kernels can run under
    shard_map per chip (the exchange leg stays whatever ``exchange``
    says — the kernels never swallow the cross-chip leg).

    ``collect_propagation`` (static, default off) makes the round also
    return the cluster-wide redundancy-ledger pair ``(slots_sent,
    slots_learned)`` — two i32 scalars folded from planes the round
    already materializes (``packets``/``incoming``), so the traced path
    adds reductions only, never a transfer.  ``slots_sent`` is the
    wire-slot count: ``eff_fanout × Σ popcount(packets)`` (exact under
    rotation sampling, where every rotation leg is a permutation read of
    the packet plane; the expectation under iid sampling).  Slots lost
    to partition masks or injected drop stay IN ``slots_sent`` — a wire
    slot that taught nobody is redundant by definition, which is exactly
    the ledger's point of view.  ``slots_learned`` recomputes the merge
    pass's learn plane definitionally (``incoming & ~known & alive``) so
    it is bit-exact across the XLA / fused-pallas / standalone-kernel
    merge paths.  Off (the default) the function body is untouched
    Python — the jaxpr is identical to the untraced round, the house
    bit-exactness invariant.
    """
    def active(state):
        packets = select_phase(state, cfg, mesh=mesh)
        ex = exchange_phase if exchange is None else exchange
        # the adaptive fan-out kwarg is only threaded when live, so
        # custom exchange hooks that predate it keep working unchanged
        kw = {} if eff_fanout is None else {"eff_fanout": eff_fanout}
        incoming = ex(packets, cfg, key, group=group,
                      drop_rate=drop_rate, **kw)
        st = merge_phase(state, incoming, cfg, mesh=mesh,
                         stamp_unit=stamp_unit)
        out = (st.known, st.stamp, st.last_learn, st.sendable,
               st.sendable_round, st.last_clamp)
        if cfg.stamp_deferred:
            out = out + (st.overlay, st.last_flush)
        if collect_propagation:
            eff = (jnp.asarray(cfg.fanout, jnp.int32) if eff_fanout is None
                   else jnp.asarray(eff_fanout, jnp.int32))
            sent = eff * jnp.sum(
                jax.lax.population_count(packets).astype(jnp.int32))
            alive_col = state.alive[:, None]
            new_words = incoming & ~state.known & jnp.where(
                alive_col, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
            learned = jnp.sum(
                jax.lax.population_count(new_words).astype(jnp.int32))
            out = out + (sent, learned)
        return out

    def quiet(state):
        out = (state.known, state.stamp, state.last_learn,
               state.sendable, state.sendable_round, state.last_clamp)
        if cfg.stamp_deferred:
            # quiet implies nothing pending: a learn keeps the gate open
            # >= transmit_window_rounds (>= STAMP_UNIT), and the cohort
            # flush fires within stamp_flush_unit-1 < STAMP_UNIT rounds
            # of it — so the overlay is zero here and stays zero
            out = out + (state.overlay, state.last_flush)
        if collect_propagation:
            # sending set provably empty: nothing shipped, nothing learned
            zero = jnp.asarray(0, jnp.int32)
            out = out + (zero, zero)
        return out

    res = jax.lax.cond(state.round - state.last_learn
                       < cfg.transmit_window_rounds,
                       active, quiet, state)
    (known, stamp, last_learn, sendable, sendable_round, last_clamp,
     *extra) = res
    if cfg.stamp_deferred:
        overlay, last_flush, *extra = extra
    if collect_propagation:
        slots_sent, slots_learned = extra

    # standalone wraparound guard: runs only when no streaming pass has
    # clamped for CLAMP_EVERY rounds (quiet/no-learn windows — the merge
    # learn pass clamps for free otherwise).  Cache-safe: the clamp only
    # re-pins stamps whose derived q-age exceeds AGE_PIN_Q
    # (>= transmit_limit_q by config validation), i.e. cells that are
    # non-sendable before AND after — the sendable invariant holds.
    # Deferred-safe for the same reason: a pending overlay cell's stale
    # nibble is fully overwritten at its flush, clamped or not.
    stamp, last_clamp = clamp_stamps(stamp, state.round + 1, last_clamp,
                                     cfg)
    nxt = state._replace(known=known, stamp=stamp, last_learn=last_learn,
                         sendable=sendable, sendable_round=sendable_round,
                         last_clamp=last_clamp, round=state.round + 1)
    if cfg.stamp_deferred:
        nxt = nxt._replace(overlay=overlay, last_flush=last_flush)
    if collect_propagation:
        return nxt, (slots_sent, slots_learned)
    return nxt


def run_rounds(state: GossipState, cfg: GossipConfig, key: jax.Array,
               num_rounds: int) -> GossipState:
    """lax.scan driver: the whole simulation stays on-device."""

    def body(carry, subkey):
        return round_step(carry, cfg, subkey), ()

    keys = jax.random.split(key, num_rounds)
    final, _ = jax.lax.scan(body, state, keys)
    return final


def push_round_step(state: GossipState, cfg: GossipConfig,
                    key: jax.Array) -> GossipState:
    """Exact *push*-gossip round as MXU matmuls (the north star's "SWIM as a
    GNN-style message-passing kernel", BASELINE.json).

    Each node picks ``fanout`` targets and SENDS its packet; delivery is a
    boolean-semiring matmul: unpack packets to a bit plane ``B[N, K]``,
    build the round's adjacency ``A[N, N]`` from the sampled targets, and
    ``incoming = (Aᵀ @ B) > 0`` — dense matmuls the MXU eats directly.
    O(N²) per round, so this is the conformance/small-N mode (the reference
    push semantics bit-for-bit at the round level); the pull kernel in
    ``round_step`` is the O(N·F) scale mode.  Budget accounting is
    identical (one decrement per selected fact per round).
    """
    n, k = cfg.n, cfg.k_facts

    sending = sending_mask(state, cfg)                        # bool[N, K]

    targets = jax.random.randint(key, (n, cfg.fanout), 0, n)  # i32[N, F]
    # adjacency: A[src, dst] = 1 if src sends to dst this round
    adj = jnp.zeros((n, n), jnp.float32)
    adj = adj.at[jnp.arange(n)[:, None], targets].set(1.0)
    adj = adj * state.alive[:, None].astype(jnp.float32)      # dead don't send

    bits = sending.astype(jnp.float32)                        # f32[N, K]
    counts = jnp.matmul(adj.T, bits,
                        preferred_element_type=jnp.float32)   # MXU [N, K]
    incoming = counts > 0.0

    alive_col = state.alive[:, None]
    new_mask = incoming & ~unpack_bits(state.known, k) & alive_col
    known = state.known | pack_bits(new_mask)
    # unconditional stamp pass (conformance mode): clamp rides it free
    nib = clamp_nibbles(stamp_nibbles(state.stamp, k, cfg.pack_stamp),
                        state.round + 1)
    if cfg.stamp_deferred:
        # the unconditional pass doubles as a cohort flush: retire any
        # pending overlay cells at their cohort quarter — the previous
        # round's, like flush_stamp_pass (pending cells always share the
        # current write quarter: a flush fires within STAMP_UNIT rounds
        # of any learn, never across a quarter boundary)
        nib = jnp.where(unpack_bits(state.overlay, k),
                        round_q(state.round), nib)
    nib = jnp.where(new_mask, round_q(state.round + 1), nib)
    stamp = pack_stamp_nibbles(nib, cfg.pack_stamp)
    last_learn = bump_last_learn(jnp.any(new_mask), state.round + 1,
                                 state.last_learn)
    # this conformance-mode kernel learns without maintaining the
    # sendable cache — invalidate so a later cached selection can't read
    # a plane that misses these learns
    out = state._replace(known=known, stamp=stamp, last_learn=last_learn,
                         sendable_round=jnp.asarray(-1, jnp.int32),
                         last_clamp=jnp.asarray(state.round + 1,
                                                jnp.int32),
                         round=state.round + 1)
    if cfg.stamp_deferred:
        out = out._replace(overlay=jnp.zeros_like(state.overlay),
                           last_flush=jnp.asarray(state.round + 1,
                                                  jnp.int32))
    return out


# -- Lamport-time wrap window ------------------------------------------------
#
# FactTable.ltime is u32.  A long-lived cluster's event clock WILL cross
# 2^32 (at the reference's continuous-broadcast rates, ~2 events/round,
# that is ~2^31 rounds — far, but a restart-with-snapshot cluster's clock
# is cumulative, and wrapping silently inverts every supersession
# decision).  The wrap story: comparisons are WINDOWED two's-complement —
# ``a`` supersedes ``b`` iff ``int32(a - b) > 0`` — which is exact as long
# as all live ltimes span < 2^31 (the "window").  Where windowing cannot
# save us (live ltimes genuinely spanning >= 2^31, i.e. facts retained for
# ~half the clock space) the guard below fails LOUD instead of silently
# mis-ordering; the invariant checker (faults/invariants.py) asserts it
# after every chaos run.

LTIME_WINDOW = 1 << 31


def ltime_newer(a, b) -> jnp.ndarray:
    """Wrap-safe ``a`` strictly supersedes ``b`` for u32 Lamport times
    (windowed two's-complement; exact while |true distance| < 2^31)."""
    return (jnp.asarray(a, jnp.uint32)
            - jnp.asarray(b, jnp.uint32)).astype(jnp.int32) > 0


def ltime_rel(ltimes, pivot) -> jnp.ndarray:
    """Signed i32 offsets of u32 ``ltimes`` relative to ``pivot`` — the
    order-preserving embedding a windowed max/argmax runs in.  Sound
    while every value is within 2^31 of ``pivot`` (guard below)."""
    return (jnp.asarray(ltimes, jnp.uint32)
            - jnp.asarray(pivot, jnp.uint32)).astype(jnp.int32)


def ltime_window_violation(facts: FactTable) -> jnp.ndarray:
    """Scalar bool: the valid facts' ltimes span >= 2^31, so windowed
    comparison can no longer order them — fail loud (the host callers
    raise; under jit, reduce and check after device_get).

    Computed on the u32 circle (no 64-bit arithmetic — the test harness
    runs with x64 disabled): sort the valid ltimes, take circular gaps
    between consecutive points; the occupied span is ``2^32 - max_gap``.
    The window holds iff the span is < 2^31, i.e. ``max_gap > 2^31``.
    All-equal ltimes make every gap 0 (span 0 — never a violation).
    """
    valid = facts.valid
    pivot = facts.ltime[jnp.argmax(valid)]
    pts = jnp.where(valid, facts.ltime, pivot)        # u32[K]
    s = jnp.sort(pts)
    gaps = jnp.roll(s, -1) - s                        # u32 circular diffs
    max_gap = jnp.max(gaps)
    return (jnp.any(valid) & (max_gap != 0)
            & (max_gap <= jnp.uint32(LTIME_WINDOW)))


# -- metrics -----------------------------------------------------------------

def coverage(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """Fraction of alive nodes that know each fact: f32[K]."""
    known = unpack_bits(state.known, cfg.k_facts)             # bool[N, K]
    alive = state.alive[:, None]
    num = jnp.sum(known & alive, axis=0).astype(jnp.float32)
    den = jnp.maximum(jnp.sum(state.alive), 1).astype(jnp.float32)
    return num / den


def fully_disseminated(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """bool[K]: every alive node knows the fact (for valid facts)."""
    cov = coverage(state, cfg)
    return jnp.where(state.facts.valid, cov >= 1.0, True)


def emit_gossip_metrics(state: GossipState, cfg: GossipConfig,
                        labels=None) -> dict:
    """Emit device-plane dissemination gauges onto the process sink.

    The model runs under jit where Python-side counters cannot fire, so
    observability is pull-based: call this between scans (bench.py does,
    after each timed block) and it summarizes the HBM-resident state into
    host scalars — one device->host sync plus an N×K unpack for coverage
    and fan-out, so never call it inside a jitted round.  Returns the
    emitted ``{name: value}`` dict so callers can embed it in artifacts.
    """
    from serf_tpu.utils import metrics

    # local import: antientropy imports from this module at load time
    from serf_tpu.models.antientropy import knowledge_agreement

    valid = state.facts.valid
    n_valid = jnp.maximum(jnp.sum(valid), 1).astype(jnp.float32)
    mean_cov = jnp.sum(jnp.where(valid, coverage(state, cfg), 0.0)) / n_valid
    # knowledge agreement — THE convergence definition (the invariant
    # checker and the SLO plane judge the same function); the per-round
    # telemetry row (models/swim.round_telemetry) inlines it only to
    # share one unpack with its coverage computation
    agreement = knowledge_agreement(state, cfg)
    # dissemination fan-out: packets each alive node would select this
    # round (the transmit-limited queue's aggregate depth, vectorized)
    fan_out = jnp.sum(sending_mask(state, cfg)).astype(jnp.float32) \
        / jnp.maximum(jnp.sum(state.alive), 1).astype(jnp.float32)
    # one device_get for the whole dict: async-copies every leaf, then a
    # single blocking wait — not one round-trip per metric
    vals = jax.device_get({
        "serf.model.gossip.round": state.round,
        "serf.model.gossip.alive": jnp.sum(state.alive),
        "serf.model.gossip.facts-valid": jnp.sum(valid),
        "serf.model.gossip.coverage": mean_cov,
        "serf.model.gossip.agreement": agreement,
        "serf.model.gossip.fan-out": fan_out,
        "serf.model.gossip.tombstones": jnp.sum(state.tombstone),
        # the overload ledger (GossipState.overflow/.injected): facts
        # clobbered while still inside their transmit window, and total
        # facts injected by any path (dropped <= offered always)
        "serf.overload.device_dropped": state.overflow,
        "serf.overload.device_offered": state.injected,
    })
    vals = {name: float(v) for name, v in vals.items()}
    for name, v in vals.items():
        metrics.gauge(name, v, labels)
    return vals
