"""Typed protocol state: clocks, members, tags, filters, messages, coordinates.

Mirrors the capability surface of reference serf-core/src/types/ (SURVEY.md §2.4)
with a Python-native (host plane) and array-native (device plane) design.
"""

from serf_tpu.types.clock import LamportClock, LamportTime
from serf_tpu.types.member import Member, MemberState, MemberStatus, Node
from serf_tpu.types.tags import Tags
from serf_tpu.types.messages import (
    MessageType,
    JoinMessage,
    LeaveMessage,
    UserEventMessage,
    UserEvents,
    PushPullMessage,
    QueryMessage,
    QueryResponseMessage,
    QueryFlag,
    ConflictResponseMessage,
    KeyRequestMessage,
    KeyResponseMessage,
    encode_message,
    decode_message,
    encode_relay_message,
)
from serf_tpu.types.filters import Filter, IdFilter, TagFilter
from serf_tpu.types.trace import TraceContext

__all__ = [
    "LamportClock",
    "LamportTime",
    "Member",
    "MemberState",
    "MemberStatus",
    "Node",
    "Tags",
    "MessageType",
    "JoinMessage",
    "LeaveMessage",
    "UserEventMessage",
    "UserEvents",
    "PushPullMessage",
    "QueryMessage",
    "QueryResponseMessage",
    "QueryFlag",
    "ConflictResponseMessage",
    "KeyRequestMessage",
    "KeyResponseMessage",
    "encode_message",
    "decode_message",
    "encode_relay_message",
    "Filter",
    "IdFilter",
    "TagFilter",
    "TraceContext",
]
