"""TraceContext: the cross-node trace wire type.

Lives in ``serf_tpu.types`` (not ``obs``) because it is carried INSIDE
wire messages (``types/messages.py``): the wire layer must stay
importable without initializing the observability package.  The
scope/propagation helpers (``trace_scope``, ``new_trace``,
``current_trace``) live in :mod:`serf_tpu.obs.trace`, which re-exports
this class — ``from serf_tpu.obs.trace import TraceContext`` remains the
canonical spelling for observability code.
"""

from __future__ import annotations

from serf_tpu import codec

#: bytes in a trace id (random; collision-safe at cluster scale)
TRACE_ID_LEN = 16


class TraceContext:
    """Compact cross-node trace context: trace id + origin + hop count.

    Immutable; ``hop()`` derives the next-hop context.  The wire form is
    codec fields (1: id bytes, 2: hops varint, 3: origin str) nested as a
    bytes field inside the carrying message, so decoders that predate the
    field skip it silently (mixed-version clusters keep interoperating).
    """

    __slots__ = ("trace_id", "origin", "hops")

    def __init__(self, trace_id: bytes, origin: str, hops: int = 0):
        self.trace_id = trace_id
        self.origin = origin
        self.hops = hops

    @property
    def hex_id(self) -> str:
        return self.trace_id.hex()

    def hop(self) -> "TraceContext":
        return TraceContext(self.trace_id, self.origin, self.hops + 1)

    def encode(self) -> bytes:
        out = codec.encode_bytes_field(1, self.trace_id)
        out += codec.encode_varint_field(2, self.hops)
        out += codec.encode_str_field(3, self.origin)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "TraceContext":
        tid, hops, origin = b"", 0, ""
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                tid = codec.as_bytes(v)
            elif f == 2:
                hops = codec.as_uint(v)
            elif f == 3:
                origin = codec.as_str(v)
        if len(tid) != TRACE_ID_LEN:
            raise codec.DecodeError(
                f"trace id must be {TRACE_ID_LEN} bytes, got {len(tid)}")
        return cls(tid, origin, hops)

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.origin == other.origin
                and self.hops == other.hops)

    def __hash__(self) -> int:
        return hash((self.trace_id, self.origin, self.hops))

    def __repr__(self) -> str:
        return (f"TraceContext({self.hex_id[:8]}…, origin={self.origin!r}, "
                f"hops={self.hops})")
