"""Wire messages and the message envelope.

Reference: serf-core/src/types/message.rs (envelope tags 1-10, encode/decode,
relay nesting), join.rs, leave.rs, user_event/, query.rs, push_pull.rs,
conflict.rs, key.rs (SURVEY.md §2.4).  Same capability, new encoding framework
(``serf_tpu.codec``): every message is `[type_byte][protobuf-style body]`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from serf_tpu import codec
from serf_tpu.types.trace import TraceContext
from serf_tpu.utils import metrics
from serf_tpu.types.clock import LamportTime
from serf_tpu.types.member import Member, Node
from serf_tpu.types.filters import Filter, decode_filter


def _decode_tctx(buf: bytes) -> Optional[TraceContext]:
    """Trace context is observability metadata: malformed bytes degrade to
    'no context' instead of failing the whole message (the fail-closed
    DecodeError contract stays scoped to protocol-bearing fields)."""
    try:
        return TraceContext.decode(buf)
    except (codec.DecodeError, TypeError, ValueError, UnicodeDecodeError):
        return None


class MessageType(enum.IntEnum):
    """Envelope tags (reference message.rs:17-124 uses the same registry).

    ``BATCH`` is this reproduction's extension (host-plane throughput
    rebuild): one envelope carrying N already-encoded messages, so the
    gossip drain amortizes one wire encode + one SWIM frame + one sendto
    over every queued broadcast instead of paying per message."""

    LEAVE = 1
    JOIN = 2
    PUSH_PULL = 3
    USER_EVENT = 4
    QUERY = 5
    QUERY_RESPONSE = 6
    CONFLICT_RESPONSE = 7
    RELAY = 8
    KEY_REQUEST = 9
    KEY_RESPONSE = 10
    BATCH = 11


class QueryFlag(enum.IntFlag):
    """reference query.rs:20-38, extended with the overload fast-fail
    bit (ISSUE 5): a responder under admission-control pressure answers
    OVERLOADED immediately instead of letting the originator time out
    silently."""

    NONE = 0
    ACK = 1
    NO_BROADCAST = 2
    OVERLOADED = 4


@dataclass(frozen=True)
class JoinMessage:
    """Join intent (reference types/join.rs:18)."""

    ltime: LamportTime
    id: str

    TYPE = MessageType.JOIN

    def encode_body(self) -> bytes:
        return codec.encode_varint_field(1, self.ltime) + codec.encode_str_field(2, self.id)

    @classmethod
    def decode_body(cls, buf: bytes) -> "JoinMessage":
        lt, nid = 0, ""
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = codec.as_uint(v)
            elif f == 2:
                nid = codec.as_str(v)
        return cls(lt, nid)


@dataclass(frozen=True)
class LeaveMessage:
    """Leave intent; ``prune`` requests full erasure (reference types/leave.rs:21)."""

    ltime: LamportTime
    id: str
    prune: bool = False

    TYPE = MessageType.LEAVE

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, self.ltime) + codec.encode_str_field(2, self.id)
        if self.prune:
            out += codec.encode_varint_field(3, 1)
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "LeaveMessage":
        lt, nid, prune = 0, "", False
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = codec.as_uint(v)
            elif f == 2:
                nid = codec.as_str(v)
            elif f == 3:
                prune = bool(codec.as_uint(v))
        return cls(lt, nid, prune)


@dataclass(frozen=True)
class UserEventMessage:
    """Named user event broadcast (reference user_event/message.rs:15)."""

    ltime: LamportTime
    name: str
    payload: bytes = b""
    cc: bool = False  # coalesce-control flag
    tctx: Optional[TraceContext] = None  # cross-node trace (obs metadata)

    TYPE = MessageType.USER_EVENT

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, self.ltime)
        out += codec.encode_str_field(2, self.name)
        if self.payload:
            out += codec.encode_bytes_field(3, self.payload)
        if self.cc:
            out += codec.encode_varint_field(4, 1)
        if self.tctx is not None:
            out += codec.encode_bytes_field(5, self.tctx.encode())
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "UserEventMessage":
        lt, name, payload, cc, tctx = 0, "", b"", False, None
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = codec.as_uint(v)
            elif f == 2:
                name = codec.as_str(v)
            elif f == 3:
                payload = codec.as_bytes(v)
            elif f == 4:
                cc = bool(codec.as_uint(v))
            elif f == 5:
                tctx = _decode_tctx(codec.as_bytes(v))
        return cls(lt, name, payload, cc, tctx)


@dataclass(frozen=True)
class UserEvents:
    """Ring-buffer cell: all events seen at one ltime
    (reference user_event/user_events.rs:19)."""

    ltime: LamportTime
    events: Tuple[UserEventMessage, ...] = ()

    def encode(self) -> bytes:
        out = codec.encode_varint_field(1, self.ltime)
        for ev in self.events:
            out += codec.encode_bytes_field(2, ev.encode_body())
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "UserEvents":
        lt = 0
        evs: List[UserEventMessage] = []
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = codec.as_uint(v)
            elif f == 2:
                evs.append(UserEventMessage.decode_body(codec.as_bytes(v)))
        return cls(lt, tuple(evs))


@dataclass(frozen=True)
class PushPullMessage:
    """Anti-entropy state summary (reference types/push_pull.rs:26-84)."""

    ltime: LamportTime
    status_ltimes: Dict[str, LamportTime] = field(default_factory=dict)
    left_members: Tuple[str, ...] = ()
    event_ltime: LamportTime = 0
    events: Tuple[UserEvents, ...] = ()
    query_ltime: LamportTime = 0

    TYPE = MessageType.PUSH_PULL

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, self.ltime)
        for nid, lt in self.status_ltimes.items():
            entry = codec.encode_str_field(1, nid) + codec.encode_varint_field(2, lt)
            out += codec.encode_bytes_field(2, entry)
        for nid in self.left_members:
            out += codec.encode_str_field(3, nid)
        out += codec.encode_varint_field(4, self.event_ltime)
        for ue in self.events:
            if ue is not None:
                out += codec.encode_bytes_field(5, ue.encode())
        out += codec.encode_varint_field(6, self.query_ltime)
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "PushPullMessage":
        lt, ev_lt, q_lt = 0, 0, 0
        sl: Dict[str, LamportTime] = {}
        left: List[str] = []
        events: List[UserEvents] = []
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = codec.as_uint(v)
            elif f == 2:
                nid, t = "", 0
                for f2, _w2, v2, _p2 in codec.iter_fields(codec.as_bytes(v)):
                    if f2 == 1:
                        nid = codec.as_str(v2)
                    elif f2 == 2:
                        t = codec.as_uint(v2)
                sl[nid] = t
            elif f == 3:
                left.append(codec.as_str(v))
            elif f == 4:
                ev_lt = codec.as_uint(v)
            elif f == 5:
                events.append(UserEvents.decode(codec.as_bytes(v)))
            elif f == 6:
                q_lt = codec.as_uint(v)
        return cls(lt, sl, tuple(left), ev_lt, tuple(events), q_lt)


@dataclass(frozen=True)
class QueryMessage:
    """Scatter query (reference types/query.rs:56-138)."""

    ltime: LamportTime
    id: int  # random query id
    from_node: Node = field(default_factory=lambda: Node(""))
    filters: Tuple[Filter, ...] = ()
    flags: QueryFlag = QueryFlag.NONE
    relay_factor: int = 0
    timeout_ns: int = 0
    name: str = ""
    payload: bytes = b""
    tctx: Optional[TraceContext] = None  # cross-node trace (obs metadata)

    TYPE = MessageType.QUERY

    def ack(self) -> bool:
        return bool(self.flags & QueryFlag.ACK)

    def no_broadcast(self) -> bool:
        return bool(self.flags & QueryFlag.NO_BROADCAST)

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, self.ltime)
        out += codec.encode_varint_field(2, self.id)
        out += codec.encode_bytes_field(3, self.from_node.encode())
        for flt in self.filters:
            out += codec.encode_bytes_field(4, flt.encode())
        out += codec.encode_varint_field(5, int(self.flags))
        out += codec.encode_varint_field(6, self.relay_factor)
        out += codec.encode_varint_field(7, self.timeout_ns)
        out += codec.encode_str_field(8, self.name)
        if self.payload:
            out += codec.encode_bytes_field(9, self.payload)
        if self.tctx is not None:
            out += codec.encode_bytes_field(10, self.tctx.encode())
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "QueryMessage":
        kw = dict(ltime=0, id=0, from_node=Node(""), flags=QueryFlag.NONE,
                  relay_factor=0, timeout_ns=0, name="", payload=b"",
                  tctx=None)
        filters: List[Filter] = []
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                kw["ltime"] = codec.as_uint(v)
            elif f == 2:
                kw["id"] = codec.as_uint(v)
            elif f == 3:
                kw["from_node"] = Node.decode(codec.as_bytes(v))
            elif f == 4:
                filters.append(decode_filter(codec.as_bytes(v)))
            elif f == 5:
                kw["flags"] = QueryFlag(codec.as_uint(v))
            elif f == 6:
                kw["relay_factor"] = codec.as_uint(v)
            elif f == 7:
                kw["timeout_ns"] = codec.as_uint(v)
            elif f == 8:
                kw["name"] = codec.as_str(v)
            elif f == 9:
                kw["payload"] = codec.as_bytes(v)
            elif f == 10:
                kw["tctx"] = _decode_tctx(codec.as_bytes(v))
        return cls(filters=tuple(filters), **kw)


@dataclass(frozen=True)
class QueryResponseMessage:
    """Ack or payload response to a query (reference types/query/response.rs:26-78)."""

    ltime: LamportTime
    id: int
    from_node: Node = field(default_factory=lambda: Node(""))
    flags: QueryFlag = QueryFlag.NONE
    payload: bytes = b""
    tctx: Optional[TraceContext] = None  # echoed from the query (obs)

    TYPE = MessageType.QUERY_RESPONSE

    def ack(self) -> bool:
        return bool(self.flags & QueryFlag.ACK)

    def overloaded(self) -> bool:
        return bool(self.flags & QueryFlag.OVERLOADED)

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, self.ltime)
        out += codec.encode_varint_field(2, self.id)
        out += codec.encode_bytes_field(3, self.from_node.encode())
        out += codec.encode_varint_field(4, int(self.flags))
        if self.payload:
            out += codec.encode_bytes_field(5, self.payload)
        if self.tctx is not None:
            out += codec.encode_bytes_field(6, self.tctx.encode())
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "QueryResponseMessage":
        lt, qid, frm, flags, payload, tctx = (
            0, 0, Node(""), QueryFlag.NONE, b"", None)
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = codec.as_uint(v)
            elif f == 2:
                qid = codec.as_uint(v)
            elif f == 3:
                frm = Node.decode(codec.as_bytes(v))
            elif f == 4:
                flags = QueryFlag(codec.as_uint(v))
            elif f == 5:
                payload = codec.as_bytes(v)
            elif f == 6:
                tctx = _decode_tctx(codec.as_bytes(v))
        return cls(lt, qid, frm, flags, payload, tctx)


@dataclass(frozen=True)
class ConflictResponseMessage:
    """Answer to a ``_serf_conflict`` internal query (reference types/conflict.rs:13-92)."""

    member: Member

    TYPE = MessageType.CONFLICT_RESPONSE

    def encode_body(self) -> bytes:
        return codec.encode_bytes_field(1, self.member.encode())

    @classmethod
    def decode_body(cls, buf: bytes) -> "ConflictResponseMessage":
        member = Member(Node(""))
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                member = Member.decode(codec.as_bytes(v))
        return cls(member)


@dataclass(frozen=True)
class KeyRequestMessage:
    """Keyring op payload (reference types/key.rs:16-157)."""

    key: bytes = b""

    TYPE = MessageType.KEY_REQUEST

    def encode_body(self) -> bytes:
        return codec.encode_bytes_field(1, self.key) if self.key else b""

    @classmethod
    def decode_body(cls, buf: bytes) -> "KeyRequestMessage":
        key = b""
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                key = codec.as_bytes(v)
        return cls(key)


@dataclass(frozen=True)
class KeyResponseMessage:
    """Per-node result of a keyring op (reference types/key.rs:16-157)."""

    result: bool = True
    message: str = ""
    keys: Tuple[bytes, ...] = ()
    primary_key: bytes = b""

    TYPE = MessageType.KEY_RESPONSE

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, 1 if self.result else 0)
        if self.message:
            out += codec.encode_str_field(2, self.message)
        for k in self.keys:
            out += codec.encode_bytes_field(3, k)
        if self.primary_key:
            out += codec.encode_bytes_field(4, self.primary_key)
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "KeyResponseMessage":
        res, msg, keys, pk = True, "", [], b""
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                res = bool(codec.as_uint(v))
            elif f == 2:
                msg = codec.as_str(v)
            elif f == 3:
                keys.append(codec.as_bytes(v))
            elif f == 4:
                pk = codec.as_bytes(v)
        return cls(res, msg, tuple(keys), pk)


@dataclass(frozen=True)
class BatchMessage:
    """N already-encoded messages in one envelope (this reproduction's
    extension; no reference analog).  ``parts`` are raw per-message
    bytes, each with its own type byte — the receiver dispatches them
    individually, so batching is transparent to every handler.  The
    envelope body is the shared varint frame sequence
    (``serf_tpu.codec.encode_frames``), not numbered fields: framing
    overhead per message is 1-2 bytes."""

    parts: Tuple[bytes, ...] = ()

    TYPE = MessageType.BATCH

    def encode_body(self) -> bytes:
        return codec.encode_frames(self.parts)

    @classmethod
    def decode_body(cls, buf: bytes) -> "BatchMessage":
        return cls(tuple(codec.decode_frames(buf)))


def encode_message_batch(raws) -> bytes:
    """One ``BATCH`` envelope around N already-encoded messages — the
    broadcast-drain entry point: the queued broadcasts' bytes are
    reused verbatim (zero re-encode), and the whole batch costs ONE
    SWIM frame + ONE wire encode + ONE sendto downstream."""
    return bytes([int(MessageType.BATCH)]) + codec.encode_frames(raws)


def decode_message_batch(buf: bytes) -> List[bytes]:
    """The raw per-message parts of an encoded ``BATCH`` envelope
    (each still carries its own type byte — feed them back through
    :func:`decode_message` / the cached variant individually)."""
    if not buf or buf[0] != int(MessageType.BATCH):
        raise codec.DecodeError("not a BATCH envelope")
    return codec.decode_frames(buf, 1)


_DECODERS = {
    MessageType.LEAVE: LeaveMessage.decode_body,
    MessageType.JOIN: JoinMessage.decode_body,
    MessageType.PUSH_PULL: PushPullMessage.decode_body,
    MessageType.USER_EVENT: UserEventMessage.decode_body,
    MessageType.QUERY: QueryMessage.decode_body,
    MessageType.QUERY_RESPONSE: QueryResponseMessage.decode_body,
    MessageType.CONFLICT_RESPONSE: ConflictResponseMessage.decode_body,
    MessageType.KEY_REQUEST: KeyRequestMessage.decode_body,
    MessageType.KEY_RESPONSE: KeyResponseMessage.decode_body,
    MessageType.BATCH: BatchMessage.decode_body,
}

Message = object  # union of the dataclasses above


def encode_message(msg) -> bytes:
    """`[type_byte][body]` (reference message.rs:372-504)."""
    return bytes([int(msg.TYPE)]) + msg.encode_body()


@dataclass(frozen=True)
class RelayMessage:
    """Relay envelope: deliver ``payload`` (an encoded message) to ``node``
    (reference message.rs relay nesting, 506-757)."""

    node: Node
    payload: bytes  # an encoded message (with its own type byte)

    TYPE = MessageType.RELAY


def encode_relay_message(node: Node, inner: bytes) -> bytes:
    body = codec.encode_bytes_field(1, node.encode()) + codec.encode_bytes_field(2, inner)
    return bytes([int(MessageType.RELAY)]) + body


def decode_message(buf: bytes):
    """Decode an envelope; returns a message dataclass or ``RelayMessage``.

    Fails closed: any malformation (wrong wire type for a field, bad utf-8,
    out-of-range enum) raises ``DecodeError`` — never an arbitrary exception.
    This is the invariant the reference's fuzz target pins
    (fuzz/fuzz_targets/messages.rs:12-16).
    """
    if not buf:
        raise codec.DecodeError("empty message")
    try:
        ty = MessageType(buf[0])
    except ValueError as e:
        raise codec.DecodeError(f"unknown message type {buf[0]}") from e
    body = buf[1:]
    try:
        if ty == MessageType.RELAY:
            node, payload = Node(""), b""
            for f, _wt, v, _p in codec.iter_fields(body):
                if f == 1:
                    node = Node.decode(codec.as_bytes(v))
                elif f == 2:
                    payload = codec.as_bytes(v)
            return RelayMessage(node, payload)
        return _DECODERS[ty](body)
    except codec.DecodeError:
        raise
    except (AttributeError, TypeError, UnicodeDecodeError, ValueError) as e:
        raise codec.DecodeError(f"malformed {ty.name} body: {e}") from e


# ---------------------------------------------------------------------------
# decode memo (host-plane throughput rebuild)
# ---------------------------------------------------------------------------

#: bounded FIFO memo for :func:`decode_message_cached`
_DECODE_CACHE_MAX = 4096
_decode_cache: Dict[bytes, object] = {}

#: the rebroadcast-heavy envelope types whose decoded dataclasses are
#: DEEPLY IMMUTABLE (frozen, tuple/bytes/str/Node fields) and therefore
#: safe to share between deliveries and co-located nodes.  PUSH_PULL is
#: deliberately excluded (it carries a mutable dict and is never
#: rebroadcast); RELAY/BATCH are containers whose inner parts get their
#: own cache entries.
_CACHEABLE_TYPES = frozenset({
    int(MessageType.LEAVE), int(MessageType.JOIN),
    int(MessageType.USER_EVENT), int(MessageType.QUERY),
    int(MessageType.QUERY_RESPONSE),
})


def decode_message_cached(buf: bytes):
    """:func:`decode_message` with a bounded memo keyed on the raw
    bytes.

    Gossip redundancy makes the host plane decode the SAME bytes many
    times: each broadcast is retransmitted ``retransmit_mult×log(n)``
    times and arrives at every peer each time — under the query-storm
    bench the hot path decoded ~20× more messages than there were
    distinct payloads, and the Python codec pass was the single largest
    loop cost.  Decoded messages are immutable (see
    ``_CACHEABLE_TYPES``), so one decode can serve every arrival.
    FIFO eviction keeps the memo bounded; a miss costs one dict probe
    over the plain decode."""
    msg = _decode_cache.get(buf)
    if msg is not None:
        metrics.incr("serf.codec.decode-cache-hit")
        return msg
    msg = decode_message(buf)
    if buf[0] in _CACHEABLE_TYPES:
        if len(_decode_cache) >= _DECODE_CACHE_MAX:
            _decode_cache.pop(next(iter(_decode_cache)))
        _decode_cache[bytes(buf)] = msg
    metrics.incr("serf.codec.decode-cache-miss")
    return msg
