"""Node tags: an ordered string->string map encoded into node metadata.

Reference: serf-core/src/types/tags.rs:28-63 — tags ride in the memberlist
node-meta blob, bounded by ``Meta.MAX_SIZE`` (512 bytes).  The bound is NOT
enforced here: as in the reference, the serf engine checks the encoded length
at construction and on ``set_tags`` (reference serf-core/src/serf/base.rs:73-83)
via ``check_meta_size``.
"""

from __future__ import annotations

from typing import Dict, Iterator, Mapping, Optional

from serf_tpu import codec

META_MAX_SIZE = 512  # memberlist Meta::MAX_SIZE equivalent


class Tags(Mapping[str, str]):
    """Immutable-ish ordered tag map with wire encode/decode."""

    __slots__ = ("_map",)

    def __init__(self, items: Optional[Mapping[str, str]] = None, **kw: str):
        m: Dict[str, str] = {}
        if items:
            m.update(items)
        m.update(kw)
        self._map = m

    def __getitem__(self, k: str) -> str:
        return self._map[k]

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def __len__(self) -> int:
        return len(self._map)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Tags):
            return self._map == other._map
        if isinstance(other, Mapping):
            return self._map == dict(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(tuple(sorted(self._map.items())))

    def __repr__(self) -> str:  # pragma: no cover
        return f"Tags({self._map!r})"

    # wire format: repeated field 1 = length-delimited (key_len-prefixed key ++ value)
    def encode(self) -> bytes:
        out = bytearray()
        for k, v in self._map.items():
            kb, vb = k.encode("utf-8"), v.encode("utf-8")
            entry = codec.encode_varint(len(kb)) + kb + vb
            out += codec.encode_length_delimited(1, entry)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Tags":
        m: Dict[str, str] = {}
        for field, _wt, value, _pos in codec.iter_fields(buf):
            if field == 1:
                if not isinstance(value, bytes):
                    raise codec.DecodeError("tags entry: expected length-delimited field")
                klen, p = codec.decode_varint(value, 0)
                if p + klen > len(value):
                    raise codec.DecodeError("tags entry: key length out of range")
                try:
                    k = value[p : p + klen].decode("utf-8")
                    v = value[p + klen :].decode("utf-8")
                except UnicodeDecodeError as e:
                    raise codec.DecodeError(f"tags entry: invalid utf-8: {e}") from e
                m[k] = v
        return cls(m)

    def encoded_len(self) -> int:
        return len(self.encode())

    def check_meta_size(self) -> None:
        """Serf-layer bound check (reference serf-core/src/serf/base.rs:73-83)."""
        n = self.encoded_len()
        if n > META_MAX_SIZE:
            raise ValueError(f"encoded tags are {n} bytes, exceeding the {META_MAX_SIZE}-byte node-meta limit")
