"""Member model: node identity, status lattice, member table entries.

Reference: serf-core/src/types/member.rs:20-230 (SURVEY.md §2.4).  Statuses form
the transition lattice driven by Lamport-gated intents (alive/leaving/left/
failed); ``MemberState`` carries the ltime of the last status change plus the
wall-time a leave/fail was observed (for reaping).
"""

from __future__ import annotations

import enum
import time as _time
from dataclasses import dataclass, field, replace
from typing import Optional

from serf_tpu import codec
from serf_tpu.types.clock import LamportTime
from serf_tpu.types.tags import Tags


class MemberStatus(enum.IntEnum):
    NONE = 0
    ALIVE = 1
    LEAVING = 2
    LEFT = 3
    FAILED = 4

    @property
    def is_gone(self) -> bool:
        return self in (MemberStatus.LEFT, MemberStatus.FAILED)


@dataclass(frozen=True)
class Node:
    """Node identity: an id string plus a resolved address.

    The reference is generic over (Id, Address); the host plane fixes Id=str
    and Address=opaque transport address (host:port tuple or loopback index).
    """

    id: str
    addr: object = None

    def encode(self) -> bytes:
        out = codec.encode_str_field(1, self.id)
        # Address field is typed so decode round-trips exactly:
        # 2 = "host:port" string, 3 = integer (loopback index), 4 = plain string.
        if self.addr is not None:
            if isinstance(self.addr, tuple) and len(self.addr) == 2:
                out += codec.encode_str_field(2, f"{self.addr[0]}:{self.addr[1]}")
            elif isinstance(self.addr, int):
                out += codec.encode_varint_field(3, self.addr)
            else:
                out += codec.encode_str_field(4, str(self.addr))
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "Node":
        nid, addr = "", None
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                nid = codec.as_str(v)
            elif f == 2:
                s = codec.as_str(v)
                host, _, port = s.rpartition(":")
                try:
                    addr = (host, int(port))
                except ValueError as e:
                    raise codec.DecodeError(f"bad host:port address {s!r}") from e
            elif f == 3:
                addr = codec.as_uint(v)
            elif f == 4:
                addr = codec.as_str(v)
        return cls(nid, addr)


@dataclass(frozen=True)
class Member:
    """Public view of a cluster member (reference member.rs:130-230)."""

    node: Node
    tags: Tags = field(default_factory=Tags)
    status: MemberStatus = MemberStatus.NONE
    protocol_version: int = 1
    delegate_version: int = 1

    def with_status(self, status: MemberStatus) -> "Member":
        return replace(self, status=status)

    def encode(self) -> bytes:
        out = codec.encode_bytes_field(1, self.node.encode())
        tb = self.tags.encode()
        if tb:
            out += codec.encode_bytes_field(2, tb)
        out += codec.encode_varint_field(3, int(self.status))
        out += codec.encode_varint_field(4, self.protocol_version)
        out += codec.encode_varint_field(5, self.delegate_version)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "Member":
        node, tags, status, pv, dv = Node(""), Tags(), MemberStatus.NONE, 1, 1
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                node = Node.decode(codec.as_bytes(v))
            elif f == 2:
                tags = Tags.decode(codec.as_bytes(v))
            elif f == 3:
                status = MemberStatus(codec.as_uint(v))
            elif f == 4:
                pv = codec.as_uint(v)
            elif f == 5:
                dv = codec.as_uint(v)
        return cls(node, tags, status, pv, dv)


@dataclass
class MemberState:
    """Member table entry (reference member.rs:20-52)."""

    member: Member
    status_time: LamportTime = 0
    leave_time: float = 0.0  # wall time the leave/failure was observed

    @property
    def id(self) -> str:
        return self.member.node.id


class IntentType(enum.IntEnum):
    JOIN = 0
    LEAVE = 1


@dataclass
class NodeIntent:
    """Buffered intent for a node not yet in the member table
    (reference member.rs NodeIntent: ty, wall_time, ltime)."""

    ty: IntentType
    ltime: LamportTime
    wall_time: float = field(default_factory=_time.monotonic)


def upsert_intent(
    intents: dict,
    node_id: str,
    ty: IntentType,
    ltime: LamportTime,
    now: Optional[float] = None,
) -> bool:
    """Keep only the freshest intent per node (reference base.rs:1820-1866).

    Returns True if the intent was stored (it is newer than what we had).
    """
    cur = intents.get(node_id)
    if cur is None or cur.ltime < ltime:
        intents[node_id] = NodeIntent(ty, ltime, now if now is not None else _time.monotonic())
        return True
    return False


def recent_intent(intents: dict, node_id: str, ty: IntentType) -> Optional[LamportTime]:
    cur = intents.get(node_id)
    if cur is not None and cur.ty == ty:
        return cur.ltime
    return None


def reap_intents(intents: dict, now: float, timeout: float) -> None:
    stale = [k for k, v in intents.items() if now - v.wall_time > timeout]
    for k in stale:
        del intents[k]
