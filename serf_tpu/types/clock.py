"""Lamport clocks.

Host plane: a thread-safe monotonic counter with ``witness`` = max-merge
(reference serf-core/src/types/clock.rs:125-172).  Device plane: Lamport times
are uint32 arrays and ``witness`` is an elementwise max — see
``serf_tpu.models.membership``.
"""

from __future__ import annotations

import threading

LamportTime = int  # host-plane representation; device plane uses uint32 lanes


class LamportClock:
    """Monotonic logical clock.

    ``time()`` reads, ``increment()`` bumps and returns the *new* (post-bump)
    value — matching the reference's ``fetch_add(1)+1``
    (serf-core/src/types/clock.rs:148-150) — and ``witness(t)`` ensures the
    local clock is at least ``t + 1``.
    """

    __slots__ = ("_value", "_lock")

    def __init__(self, start: int = 0):
        self._value = start
        self._lock = threading.Lock()

    def time(self) -> LamportTime:
        return self._value

    def increment(self) -> LamportTime:
        with self._lock:
            self._value += 1
            return self._value

    def witness(self, t: LamportTime) -> None:
        """CAS-loop max in the reference; a guarded max here."""
        with self._lock:
            if self._value <= t:
                self._value = t + 1

    def __repr__(self) -> str:  # pragma: no cover
        return f"LamportClock({self._value})"
