"""Query filters: restrict query delivery by node id or tag regex.

Reference: serf-core/src/types/filter.rs:74-97 and filter/tag_filter.rs:16-79
(``Filter::{Id(..), Tag(TagFilter{tag, expr})}``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Tuple

from serf_tpu import codec


class Filter:
    """Base class; subclasses implement ``encode`` and ``matches``."""

    KIND: int = -1

    def encode(self) -> bytes:  # pragma: no cover - abstract
        raise NotImplementedError

    def matches(self, node_id: str, tags) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class IdFilter(Filter):
    ids: Tuple[str, ...]

    KIND = 0

    def encode(self) -> bytes:
        out = codec.encode_varint_field(1, self.KIND)
        for nid in self.ids:
            out += codec.encode_str_field(2, nid)
        return out

    def matches(self, node_id: str, tags) -> bool:
        return node_id in self.ids


@dataclass(frozen=True)
class TagFilter(Filter):
    tag: str
    expr: str  # regex source; validated + compiled once at construction

    KIND = 1

    def __post_init__(self):
        object.__setattr__(self, "_compiled", re.compile(self.expr))

    def encode(self) -> bytes:
        out = codec.encode_varint_field(1, self.KIND)
        out += codec.encode_str_field(3, self.tag)
        out += codec.encode_str_field(4, self.expr)
        return out

    def matches(self, node_id: str, tags) -> bool:
        val = tags.get(self.tag) if tags is not None else None
        if val is None:
            return False
        return self._compiled.search(val) is not None


def decode_filter(buf: bytes) -> Filter:
    kind = None
    ids = []
    tag, expr = "", ""
    for f, _wt, v, _p in codec.iter_fields(buf):
        if f == 1:
            kind = codec.as_uint(v)
        elif f == 2:
            ids.append(codec.as_str(v))
        elif f == 3:
            tag = codec.as_str(v)
        elif f == 4:
            expr = codec.as_str(v)
    if kind == IdFilter.KIND:
        return IdFilter(tuple(ids))
    if kind == TagFilter.KIND:
        try:
            return TagFilter(tag, expr)
        except re.error as e:
            raise codec.DecodeError(f"invalid tag-filter regex {expr!r}: {e}") from e
    raise codec.DecodeError(f"unknown filter kind {kind}")
