"""Device-mesh sharding: single-host node-axis sharding (``mesh``) and
multi-host DCN x ICI hybrid meshes (``multihost``)."""

from serf_tpu.parallel.mesh import NODE_AXIS, make_mesh, shard_state, state_shardings

__all__ = ["NODE_AXIS", "make_mesh", "shard_state", "state_shardings"]
