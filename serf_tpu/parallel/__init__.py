"""Device-mesh sharding: single-host node-axis sharding (``mesh``), the
flagship sharded exchange with explicit ICI schedules (``ring``), and
multi-host DCN x ICI hybrid meshes (``multihost``)."""

from serf_tpu.parallel.mesh import (
    NODE_AXIS,
    best_device_count,
    make_mesh,
    shard_state,
    state_shardings,
)
from serf_tpu.parallel.ring import (
    EXCHANGE_SCHEDULES,
    exchange_sharded,
    sharded_round_step,
)

__all__ = ["NODE_AXIS", "best_device_count", "make_mesh", "shard_state",
           "state_shardings", "EXCHANGE_SCHEDULES", "exchange_sharded",
           "sharded_round_step"]
