"""Device-mesh sharding for the cluster simulation.

The scaling story (SURVEY.md §5 "long-context" translation): the member
table and every per-node array shard across chips on the node dimension —
the gossip analog of data/sequence parallelism.  Cross-shard gossip edges
are handled by XLA-inserted collectives: ``packets[srcs]`` with a sharded
``packets`` and replicated index space becomes an all-gather of the packed
packet words (N×W uint32 is small: 32 MB at 1M nodes), which rides ICI.

We annotate shardings with ``NamedSharding``/``PartitionSpec`` and let
GSPMD place the collectives — the pick-a-mesh / annotate / let-XLA-insert
recipe — rather than hand-scheduling shard_map loops.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from serf_tpu.models.swim import ClusterState

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.array(devices), (NODE_AXIS,))


def _spec_for(path: str, arr) -> P:
    """Per-node arrays shard on their first (N) axis; facts and scalars are
    replicated."""
    if arr.ndim == 0:
        return P()
    # fact-table arrays are K-major and replicated; everything under
    # 'gossip.facts' or with a non-N leading dim stays replicated
    if "facts" in path:
        return P()
    if "adj_index" in path:
        return P()
    return P(NODE_AXIS)


def state_shardings(state: ClusterState, mesh: Mesh):
    """A pytree of NamedShardings matching ``state``."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    specs = []
    for path, leaf in flat:
        pstr = jax.tree_util.keystr(path)
        specs.append(NamedSharding(mesh, _spec_for(pstr, leaf)))
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_state(state: ClusterState, mesh: Mesh) -> ClusterState:
    return jax.device_put(state, state_shardings(state, mesh))
