"""Device-mesh sharding for the cluster simulation.

The scaling story (SURVEY.md §5 "long-context" translation): the member
table and every per-node array shard across chips on the node dimension —
the gossip analog of data/sequence parallelism.  Cross-shard gossip edges
are handled by XLA-inserted collectives: ``packets[srcs]`` with a sharded
``packets`` and replicated index space becomes an all-gather of the packed
packet words (N×W uint32 is small: 32 MB at 1M nodes), which rides ICI.

We annotate shardings with ``NamedSharding``/``PartitionSpec`` and let
GSPMD place the collectives — the pick-a-mesh / annotate / let-XLA-insert
recipe — for every elementwise/rolled phase; the one genuinely
cross-chip leg of the flagship round (the gossip exchange) is EXPLICIT
under ``shard_map`` in ``serf_tpu.parallel.ring`` (ring ppermute vs
all-gather, selectable per config) so its ICI schedule is an authored
decision, not a lowering accident.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.array(devices), (NODE_AXIS,))


def best_device_count(n: int, available: int) -> int:
    """Largest device count <= ``available`` that divides ``n`` — the
    graceful pick for N-not-divisible-by-P deployments (a 1M-node sim on
    a 7-device pool runs on 4 chips rather than crashing or silently
    falling back to one)."""
    for d in range(max(1, min(available, n)), 0, -1):
        if n % d == 0:
            return d
    return 1


# QueryState per-node planes are [Q, N]: the node axis is SECOND
_QUERY_QN_FIELDS = frozenset(
    {"eligible", "attempted", "acked", "responded", "resp_value"})
# QueryState Q-major vectors are replicated (Q is small and global).
# "ltime"/"valid" only reach these checks for QueryState — the fact-table
# fields of the same name are caught by the "facts" ancestor check first.
_QUERY_Q_FIELDS = frozenset(
    {"origin", "fact_slot", "deadline", "want_ack", "ltime", "valid"})
# K-sized (fact-ring) and otherwise cluster-global planes: every chip
# needs the whole thing.  slot_round is the overflow accountant's i32[K]
# clock (PR 5) — sharding it over the node axis would be semantically
# wrong (it is per ring SLOT, not per node) and forces GSPMD reshards in
# the inject path.  The adaptive-control vectors (ControlState.knobs/
# .streak, PR 11) are per-KNOB, cluster-global by definition — one
# control law for the whole cluster, every chip reads the same values.
_REPLICATED_LEAVES = frozenset({"adj_index", "slot_round", "knobs",
                                "streak"})
# DeviceFaultSchedule (faults.device) chaos masks: [P, N] per-phase
# group/down planes shard on their SECOND axis; per-phase loss rates
# ([P]) are replicated.
_FAULT_PN_FIELDS = frozenset({"down"})


def _path_names(path) -> list:
    """Exact attribute/key names along a tree path (no substring traps)."""
    names = []
    for entry in path:
        name = getattr(entry, "name", None) or getattr(entry, "key", None)
        if name is not None:
            names.append(str(name))
    return names


def _spec_for(path, arr) -> P:
    """Per-node arrays shard on their first (N) axis; facts, ring-slot
    planes, scalars, and query-slot metadata are replicated; query [Q, N]
    planes and fault-schedule [P, N] masks shard on their second axis.
    New N-leading leaves need no registration: the deferred-stamp
    ``overlay`` (u32[N, W]) lands on ``P(NODE_AXIS)`` through the default
    rule and its ``last_flush`` scalar replicates, exactly like the
    stamp plane and ``last_clamp`` they amend."""
    if not hasattr(arr, "ndim") or arr.ndim == 0:
        # python scalars (static per-phase round counts) and 0-d arrays
        return P()
    names = _path_names(path)
    leaf = names[-1] if names else ""
    # fact-table arrays are K-major and replicated; everything under
    # 'gossip.facts' or with a non-N leading dim stays replicated
    if "facts" in names:
        return P()
    if leaf in _REPLICATED_LEAVES:
        return P()
    if leaf in _QUERY_QN_FIELDS:
        return P(None, NODE_AXIS)
    if leaf in _QUERY_Q_FIELDS:
        return P()
    # chaos masks (faults.device.DeviceFaultSchedule): [P, N] planes —
    # "group" is [N] in ClusterState (node-sharded below) but [P, N] in
    # a fault schedule, so dispatch on rank
    if leaf in _FAULT_PN_FIELDS or (leaf == "group" and arr.ndim == 2):
        return P(None, NODE_AXIS)
    if leaf == "drop":
        return P()
    return P(NODE_AXIS)


def partition_specs(state):
    """A pytree of raw ``PartitionSpec``s matching ``state`` — the same
    per-leaf placement rules as :func:`state_shardings`, shaped for
    ``shard_map`` ``in_specs`` (which takes specs, not NamedShardings).
    The in-collective telemetry leg (``parallel.ring``) feeds the whole
    GossipState through one shard_map with these specs so its placement
    can never drift from the state sharding that GSPMD runs."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    return jax.tree_util.tree_unflatten(
        treedef, [_spec_for(path, leaf) for path, leaf in flat])


def state_shardings(state, mesh: Mesh):
    """A pytree of NamedShardings matching ``state`` (works for
    ClusterState, GossipState, QueryState, or any composite of them)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    specs = [NamedSharding(mesh, _spec_for(path, leaf))
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_state(state, mesh: Mesh):
    return jax.device_put(state, state_shardings(state, mesh))


def emit_shard_metrics(n_devices: int, schedule: str,
                       exchange_bytes_per_chip: float,
                       rps: Optional[float] = None, labels=None) -> dict:
    """Emit the sharded-flagship gauges onto the process sink (bench.py
    calls this from its ``sharded`` section; every name is README-
    documented and lint-enforced).  ``schedule`` rides as a label so the
    ring and all-gather legs of an A/B stay distinguishable."""
    from serf_tpu.utils import metrics

    vals = {
        "serf.shard.devices": float(n_devices),
        "serf.shard.exchange-bytes-per-chip": float(exchange_bytes_per_chip),
        "serf.shard.rps": float(rps) if rps is not None else None,
    }
    if vals["serf.shard.rps"] is None:
        del vals["serf.shard.rps"]
    lab = dict(labels or {}, schedule=schedule)
    for name, v in vals.items():
        metrics.gauge(name, v, lab)
    return vals
