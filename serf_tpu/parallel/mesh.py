"""Device-mesh sharding for the cluster simulation.

The scaling story (SURVEY.md §5 "long-context" translation): the member
table and every per-node array shard across chips on the node dimension —
the gossip analog of data/sequence parallelism.  Cross-shard gossip edges
are handled by XLA-inserted collectives: ``packets[srcs]`` with a sharded
``packets`` and replicated index space becomes an all-gather of the packed
packet words (N×W uint32 is small: 32 MB at 1M nodes), which rides ICI.

We annotate shardings with ``NamedSharding``/``PartitionSpec`` and let
GSPMD place the collectives — the pick-a-mesh / annotate / let-XLA-insert
recipe — rather than hand-scheduling shard_map loops.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from serf_tpu.models.swim import ClusterState

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.array(devices), (NODE_AXIS,))


# QueryState per-node planes are [Q, N]: the node axis is SECOND
_QUERY_QN_FIELDS = frozenset(
    {"eligible", "attempted", "acked", "responded", "resp_value"})
# QueryState Q-major vectors are replicated (Q is small and global).
# "ltime"/"valid" only reach these checks for QueryState — the fact-table
# fields of the same name are caught by the "facts" ancestor check first.
_QUERY_Q_FIELDS = frozenset(
    {"origin", "fact_slot", "deadline", "want_ack", "ltime", "valid"})


def _path_names(path) -> list:
    """Exact attribute/key names along a tree path (no substring traps)."""
    names = []
    for entry in path:
        name = getattr(entry, "name", None) or getattr(entry, "key", None)
        if name is not None:
            names.append(str(name))
    return names


def _spec_for(path, arr) -> P:
    """Per-node arrays shard on their first (N) axis; facts, scalars, and
    query-slot metadata are replicated; query [Q, N] planes shard on their
    second axis."""
    if arr.ndim == 0:
        return P()
    names = _path_names(path)
    leaf = names[-1] if names else ""
    # fact-table arrays are K-major and replicated; everything under
    # 'gossip.facts' or with a non-N leading dim stays replicated
    if "facts" in names:
        return P()
    if leaf == "adj_index":
        return P()
    if leaf in _QUERY_QN_FIELDS:
        return P(None, NODE_AXIS)
    if leaf in _QUERY_Q_FIELDS:
        return P()
    return P(NODE_AXIS)


def state_shardings(state, mesh: Mesh):
    """A pytree of NamedShardings matching ``state`` (works for
    ClusterState, GossipState, QueryState, or any composite of them)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    specs = [NamedSharding(mesh, _spec_for(path, leaf))
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_state(state, mesh: Mesh):
    return jax.device_put(state, state_shardings(state, mesh))
