"""Ring-pipelined gossip exchange: ppermute block rotation over ICI.

The default multi-chip round (`models/dissemination.round_step` under a
node-sharded mesh) lets GSPMD turn ``packets[srcs]`` into an **all-gather**
of the packed packet plane — simple, but it materializes the full N×W
uint32 packet array on every chip (32 MB at 1M nodes) and puts one big
collective on the critical path.

This module is the ring-attention-style alternative (SURVEY.md §5's
"where ring-attention-style SPMD decomposition would go"): under
``shard_map``, each device keeps only its N/D-sized packet block and the
blocks rotate around the ring with ``lax.ppermute``, one hop per step.
At hop h device d holds the block of shard (d − h) mod D; each node
resolves the sampled sources that live in the visiting block.  After D
hops every source has been resolved — **bit-identical to the all-gather
round** (same sampled sources, same merge), with peak memory N/D×W per
chip and D point-to-point neighbor transfers riding the ICI ring instead
of one global collective.

Use when the packet plane dominates HBM or the all-gather dominates the
round; the parity test pins bit-equality against ``round_step``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax exposes it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    bump_last_learn,
    clamp_stamps,
    learn_stamp_pass,
    select_words,
)
from serf_tpu.parallel.mesh import NODE_AXIS


def _ring_gather(packets_local: jnp.ndarray, srcs_local: jnp.ndarray,
                 n_local: int, n_devices: int) -> jnp.ndarray:
    """Inside shard_map: resolve global source indices by rotating packet
    blocks around the ring.

    packets_local: u32[Nl, W] — this shard's packet block
    srcs_local:    i32[Nl, F] — global source ids sampled by local nodes
    returns:       u32[Nl, W] — bitwise-OR of the packets of all sources
    """
    me = jax.lax.axis_index(NODE_AXIS)
    perm = [(d, (d + 1) % n_devices) for d in range(n_devices)]

    def resolve(visiting, h, acc):
        visiting_shard = (me - h) % n_devices
        mask = (srcs_local // n_local) == visiting_shard      # bool[Nl, F]
        idx = srcs_local % n_local                            # i32[Nl, F]
        got = visiting[idx]                                   # u32[Nl, F, W]
        got = jnp.where(mask[:, :, None], got, jnp.uint32(0))
        return acc | jax.lax.reduce(got, jnp.uint32(0),
                                    jnp.bitwise_or, (1,))     # u32[Nl, W]

    def hop(carry, h):
        visiting, acc = carry
        acc = resolve(visiting, h, acc)
        # rotate: my block moves to the next device; I receive the previous
        visiting = jax.lax.ppermute(visiting, NODE_AXIS, perm)
        return (visiting, acc), ()

    acc0 = jnp.zeros_like(packets_local)
    if n_devices == 1:
        return resolve(packets_local, 0, acc0)
    # D-1 rotations suffice: the last visiting block is resolved in place
    # (a final rotation would ship a block nobody reads)
    (visiting, acc), _ = jax.lax.scan(hop, (packets_local, acc0),
                                      jnp.arange(n_devices - 1))
    return resolve(visiting, n_devices - 1, acc)


def round_step_ring(state: GossipState, cfg: GossipConfig, key: jax.Array,
                    mesh, group=None) -> GossipState:
    """One gossip round with the ring-pipelined exchange.

    Bit-identical to ``round_step(state, cfg, key, group)`` for the same
    inputs (same RNG stream → same sampled sources, same Lamport merge);
    only the collective schedule differs.  Requires ``cfg.n`` divisible by
    the mesh size.
    """
    n, k, w = cfg.n, cfg.k_facts, cfg.words
    n_devices = mesh.shape[NODE_AXIS]
    if n % n_devices != 0:
        raise ValueError(f"n={n} not divisible by mesh size {n_devices}")
    n_local = n // n_devices

    # phases 1+2 exactly as round_step (elementwise; GSPMD shards freely),
    # including the cached selection when the sendable plane is valid
    # (AND `known` — stale cache bits for retired slots, see
    # GossipState.sendable_round)
    if cfg.use_sendable_cache:
        packets = jax.lax.cond(
            state.sendable_round == state.round,
            lambda s: jnp.where(s.alive[:, None],
                                s.sendable & s.known, jnp.uint32(0)),
            lambda s: select_words(s, cfg),
            state)
    else:
        packets = select_words(state, cfg)                    # u32[N, W]

    srcs = jax.random.randint(key, (n, cfg.fanout), 0, n)     # i32[N, F]
    if group is not None:
        # Partition mask, evaluated on the sampler side so the ring kernel
        # stays a pure gather: disallowed cross-group samples are
        # substituted with SELF.  Parity-safe: a node's sending bits are
        # always a subset of its known bits (budgets only exist for known
        # facts), so OR-ing its own packets into `incoming` changes no
        # merge outcome — exactly like round_step's zeroing.
        allowed = group[srcs] == group[:, None]               # bool[N, F]
        srcs = jnp.where(allowed, srcs, jnp.arange(n)[:, None])
    exchange = shard_map(
        functools.partial(_ring_gather, n_local=n_local,
                          n_devices=n_devices),
        mesh=mesh,
        in_specs=(P(NODE_AXIS, None), P(NODE_AXIS, None)),
        out_specs=P(NODE_AXIS, None),
    )
    incoming = exchange(packets, srcs)

    alive_col = state.alive[:, None]
    new_words = incoming & ~state.known & jnp.where(
        alive_col, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    known = state.known | new_words
    learned_any = jnp.any(new_words != 0)

    # stamp learn pass gated on learned_any exactly as round_step phase 5
    # (bit-exact identity when skipped), with the sendable-cache
    # recompute riding the same pass — keeps the ring bit-identical to
    # the all-gather round INCLUDING the cache, so the ring schedule
    # gets the same cached-selection saving (without this the ring leg
    # of any A/B pays the full stamp-plane selection read every round)
    def stamp_learns(_):
        # THE shared learn/clamp/cache pass (dissemination.
        # learn_stamp_pass) — one definition keeps the ring leg
        # bit-identical to round_step's merge by construction
        stamp2, send2, sr2 = learn_stamp_pass(
            state.stamp, known, new_words, state.round + 1, cfg,
            state.sendable)
        return stamp2, send2, sr2, jnp.asarray(state.round + 1, jnp.int32)

    stamp, sendable, sendable_round, last_clamp = jax.lax.cond(
        learned_any, stamp_learns,
        lambda _: (state.stamp, state.sendable, state.sendable_round,
                   state.last_clamp),
        None)
    stamp, last_clamp = clamp_stamps(stamp, state.round + 1, last_clamp,
                                     cfg)
    last_learn = bump_last_learn(learned_any, state.round + 1,
                                 state.last_learn)
    return state._replace(known=known, stamp=stamp, last_learn=last_learn,
                          sendable=sendable, sendable_round=sendable_round,
                          last_clamp=last_clamp, round=state.round + 1)
