"""The flagship sharded exchange: explicit ICI schedules under shard_map.

THE one sharded gossip round in the tree (ISSUE 6): ``cluster_round``
with a mesh routes its gossip exchange through :func:`exchange_sharded`,
which produces an ``incoming`` plane bit-identical to
``dissemination.exchange_phase`` (same RNG stream, same group/loss
masking, bitwise-OR accumulation — order-free) while keeping the packet
plane node-sharded: each chip streams only its N/P block per round and
only packet words ride the interconnect (fact words + stamps travel AS
the packed exchange; no replicated-plane rewrites).

Two ICI schedules, selectable per config (``ClusterConfig.
exchange_schedule``) and settled analytically in
``accounting.ici_round_traffic`` — the CPU virtual mesh measures
collective *schedule shape* (dispatch count, materialization), not ICI
bandwidth, so MULTICHIP_AB.json's CPU timings are not dispositive:

- ``"ring"``: the packet blocks rotate around the device ring with
  ``lax.ppermute``, one neighbor hop per step; each hop resolves the
  rows the visiting block can serve.  D-1 hops ship (D-1)×block bytes
  per chip — the same wire total as the all-gather — but peak HBM stays
  at 2 blocks and each hop's transfer overlaps the previous hop's
  resolve (ring-attention-style SPMD, SURVEY.md §5).
- ``"allgather"``: one explicit ``lax.all_gather`` of the packet plane,
  then local contiguous slices (rotation) or a local gather (iid).  One
  collective dispatch, but the full N×W plane materializes on every
  chip.

Both sampling modes are covered: ``rotation`` (the production flagship —
every peer read is a contiguous roll, assembled under the ring schedule
from at most two visiting-block slices per offset, still no random
gather) and ``iid`` (the data-dependent gather mode the original
ring-vs-allgather A/B measured).

Edge cases: a mesh whose size does not divide ``n`` falls back to the
unsharded ``exchange_phase`` (GSPMD lowers it over whatever sharding the
operands carry — bit-identical, just not schedule-authored) and records
a ``shard-fallback`` flight event; a 1-device mesh degenerates to the
local resolve with no collective.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax exposes it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    exchange_phase,
    rolled_rows,
    round_step,
    sample_offsets,
)
from serf_tpu.parallel.mesh import NODE_AXIS

#: the legal ICI schedules (ClusterConfig.exchange_schedule validates
#: against this; accounting.ici_round_traffic models both)
EXCHANGE_SCHEDULES = ("ring", "allgather")


def _ring_scan(pk, grp, resolve, n_devices):
    """Shared D-hop ring driver: rotate (packets, group) blocks one
    neighbor per hop, resolving the visiting block each hop.  The final
    visiting block is resolved in place — a D-th rotation would ship a
    block nobody reads."""
    acc0 = jnp.zeros_like(pk)
    if n_devices == 1:
        return resolve(pk, grp, 0, acc0)
    perm = [(d, (d + 1) % n_devices) for d in range(n_devices)]

    def hop(carry, h):
        vis_pk, vis_grp, acc = carry
        acc = resolve(vis_pk, vis_grp, h, acc)
        vis_pk = jax.lax.ppermute(vis_pk, NODE_AXIS, perm)
        if vis_grp is not None:
            vis_grp = jax.lax.ppermute(vis_grp, NODE_AXIS, perm)
        return (vis_pk, vis_grp, acc), ()

    (vis_pk, vis_grp, acc), _ = jax.lax.scan(
        hop, (pk, grp, acc0), jnp.arange(n_devices - 1))
    return resolve(vis_pk, vis_grp, n_devices - 1, acc)


def _rotation_ring_leg(pk, offs, grp, lost, eff, *, n, n_local, n_devices,
                       fanout):
    """Rotation sampling over the ring schedule (inside shard_map).

    Each fanout offset's rolled read ``packets[(i + off) % n]`` is, for
    this chip's receivers, a contiguous circular range of global rows —
    it intersects each visiting block in at most one run, and
    ``rolled_rows(visiting, off % n_local)`` lays both possible runs
    (the tail of shard ``s0 = start//n_local`` and the head of shard
    ``s0+1``) at exactly the right local positions.  So the assembly is
    concat + contiguous dynamic slices per hop — no random gather, the
    same property the rotation mode exists for.
    """
    me = jax.lax.axis_index(NODE_AXIS)
    gstart = me * n_local
    j = jnp.arange(n_local, dtype=jnp.int32)

    def resolve(vis_pk, vis_grp, h, acc):
        s = (me - h) % n_devices
        dbl_pk = jnp.concatenate([vis_pk, vis_pk], axis=0)
        dbl_grp = (jnp.concatenate([vis_grp, vis_grp], axis=0)
                   if vis_grp is not None else None)
        for f in range(fanout):
            start = (gstart + offs[f]) % n
            r = start % n_local
            s0 = start // n_local
            rolled = rolled_rows(vis_pk, r, doubled=dbl_pk)
            # receivers j < n_local - r read shard s0's tail; the rest
            # read shard (s0+1)'s head.  Both conjuncts apply when D=1.
            sel = (((s == s0) & (j < n_local - r))
                   | ((s == (s0 + 1) % n_devices) & (j >= n_local - r)))
            if vis_grp is not None:
                sel = sel & (rolled_rows(vis_grp, r, doubled=dbl_grp)
                             == grp)
            if lost is not None:
                sel = sel & ~lost[f]
            if eff is not None:
                sel = sel & (jnp.asarray(f, jnp.int32) < eff)
            acc = acc | jnp.where(sel[:, None], rolled, jnp.uint32(0))
        return acc

    return _ring_scan(pk, grp, resolve, n_devices)


def _rotation_allgather_leg(pk, offs, grp, lost, eff, *, n, n_local,
                            fanout):
    """Rotation sampling over the all-gather schedule: one collective,
    then the fanout rolled reads are local contiguous slices of the
    (doubled) gathered plane."""
    me = jax.lax.axis_index(NODE_AXIS)
    gstart = me * n_local
    full = jax.lax.all_gather(pk, NODE_AXIS, tiled=True)        # u32[N, W]
    dbl = jnp.concatenate([full, full], axis=0)
    dbl_grp = None
    if grp is not None:
        fgrp = jax.lax.all_gather(grp, NODE_AXIS, tiled=True)
        dbl_grp = jnp.concatenate([fgrp, fgrp], axis=0)
    acc = jnp.zeros_like(pk)
    for f in range(fanout):
        start = (gstart + offs[f]) % n
        contrib = jax.lax.dynamic_slice_in_dim(dbl, start, n_local, axis=0)
        sel = None
        if grp is not None:
            peer_grp = jax.lax.dynamic_slice_in_dim(dbl_grp, start,
                                                    n_local, axis=0)
            sel = peer_grp == grp
        if lost is not None:
            sel = ~lost[f] if sel is None else (sel & ~lost[f])
        if sel is not None:
            contrib = jnp.where(sel[:, None], contrib, jnp.uint32(0))
        if eff is not None:
            contrib = jnp.where(jnp.asarray(f, jnp.int32) < eff,
                                contrib, jnp.uint32(0))
        acc = acc | contrib
    return acc


def _iid_ring_leg(pk, srcs, grp, lost, eff, *, n_local, n_devices):
    """iid sampling over the ring schedule: rotate blocks; each hop, the
    sampled sources living in the visiting block resolve by local
    gather (u32[Nl, F, W] masked OR-reduce)."""
    me = jax.lax.axis_index(NODE_AXIS)
    fmask = (jnp.arange(srcs.shape[1], dtype=jnp.int32) < eff
             if eff is not None else None)

    def resolve(vis_pk, vis_grp, h, acc):
        s = (me - h) % n_devices
        here = (srcs // n_local) == s                 # bool[Nl, F]
        idx = srcs % n_local                          # i32[Nl, F]
        got = vis_pk[idx]                             # u32[Nl, F, W]
        ok = here
        if vis_grp is not None:
            ok = ok & (vis_grp[idx] == grp[:, None])
        if lost is not None:
            ok = ok & ~lost
        if fmask is not None:
            ok = ok & fmask[None, :]
        got = jnp.where(ok[:, :, None], got, jnp.uint32(0))
        return acc | jax.lax.reduce(got, jnp.uint32(0),
                                    jnp.bitwise_or, (1,))

    return _ring_scan(pk, grp, resolve, n_devices)


def _iid_allgather_leg(pk, srcs, grp, lost, eff):
    """iid sampling over the all-gather schedule: materialize the plane,
    gather the sampled sources locally, mask, OR-reduce."""
    full = jax.lax.all_gather(pk, NODE_AXIS, tiled=True)        # u32[N, W]
    got = full[srcs]                                  # u32[Nl, F, W]
    ok = None
    if grp is not None:
        fgrp = jax.lax.all_gather(grp, NODE_AXIS, tiled=True)
        ok = fgrp[srcs] == grp[:, None]
    if lost is not None:
        ok = ~lost if ok is None else (ok & ~lost)
    if eff is not None:
        fmask = (jnp.arange(srcs.shape[1], dtype=jnp.int32)
                 < eff)[None, :]
        ok = fmask if ok is None else (ok & fmask)
    if ok is not None:
        got = jnp.where(ok[:, :, None], got, jnp.uint32(0))
    return jax.lax.reduce(got, jnp.uint32(0), jnp.bitwise_or, (1,))


def exchange_sharded(packets: jnp.ndarray, cfg: GossipConfig,
                     key: jax.Array, group=None, drop_rate=None,
                     eff_fanout=None, *,
                     mesh, schedule: str = "ring") -> jnp.ndarray:
    """The sharded exchange leg — a drop-in for
    ``dissemination.exchange_phase`` (``round_step``'s ``exchange``
    hook) that is bit-identical for the same ``key``: the RNG splits,
    sample shapes, and mask semantics mirror ``exchange_phase`` line
    for line, and bitwise-OR accumulation is order-free, so only the
    collective schedule differs."""
    if schedule not in EXCHANGE_SCHEDULES:
        raise ValueError(f"unknown exchange schedule {schedule!r} "
                         f"(one of {EXCHANGE_SCHEDULES})")
    n = packets.shape[0]
    d = mesh.shape[NODE_AXIS]
    if n % d != 0:
        # graceful N-not-divisible-by-P: GSPMD lowers the unsharded
        # exchange over whatever sharding the operands carry —
        # bit-identical, just not schedule-authored.  Recorded loud so
        # an 8-chip deployment that silently lost its authored schedule
        # is visible in the flight recorder.
        from serf_tpu import obs
        obs.record("shard-fallback", op="exchange_sharded", n=n,
                   devices=d, reason="n % devices != 0; GSPMD lowering")
        return exchange_phase(packets, cfg, key, group=group,
                              drop_rate=drop_rate, eff_fanout=eff_fanout)
    n_local = n // d
    if drop_rate is not None:
        key, k_drop = jax.random.split(key)
    rotation = cfg.peer_sampling == "rotation"
    if rotation:
        sample = sample_offsets(key, cfg.fanout, n)             # i32[F]
        lost = (jax.random.bernoulli(k_drop, drop_rate, (cfg.fanout, n))
                if drop_rate is not None else None)
        sample_spec, lost_spec = P(), P(None, NODE_AXIS)
    else:
        sample = jax.random.randint(key, (n, cfg.fanout), 0, n)
        lost = (jax.random.bernoulli(k_drop, drop_rate, (n, cfg.fanout))
                if drop_rate is not None else None)
        sample_spec, lost_spec = P(NODE_AXIS, None), P(NODE_AXIS, None)

    operands = [packets, sample]
    specs = [P(NODE_AXIS, None), sample_spec]
    if group is not None:
        operands.append(group)
        specs.append(P(NODE_AXIS))
    if lost is not None:
        operands.append(lost)
        specs.append(lost_spec)
    if eff_fanout is not None:
        # the adaptive fan-out scalar is replicated: every chip masks
        # the same trailing offsets
        operands.append(jnp.asarray(eff_fanout, jnp.int32))
        specs.append(P())
    has_group, has_lost = group is not None, lost is not None
    has_eff = eff_fanout is not None

    def leg(pk, sample, *rest):
        i = 0
        grp = rest[i] if has_group else None
        i += has_group
        lo = rest[i] if has_lost else None
        i += has_lost
        eff = rest[i] if has_eff else None
        if rotation and schedule == "ring":
            return _rotation_ring_leg(pk, sample, grp, lo, eff, n=n,
                                      n_local=n_local, n_devices=d,
                                      fanout=cfg.fanout)
        if rotation:
            return _rotation_allgather_leg(pk, sample, grp, lo, eff, n=n,
                                           n_local=n_local,
                                           fanout=cfg.fanout)
        if schedule == "ring":
            return _iid_ring_leg(pk, sample, grp, lo, eff,
                                 n_local=n_local, n_devices=d)
        return _iid_allgather_leg(pk, sample, grp, lo, eff)

    ex = shard_map(leg, mesh=mesh, in_specs=tuple(specs),
                   out_specs=P(NODE_AXIS, None))
    return ex(*operands)


def round_telemetry_sharded(state, cfg, mesh, with_cols: bool = False):
    """The in-collective telemetry row (ISSUE 15 tentpole): the SAME
    ``f32[len(TELEMETRY_FIELDS)]`` row ``models/swim.round_telemetry``
    computes, produced as fused O(fields) collective legs on the
    exchange mesh instead of reducing over gathered N-planes.

    Three legs, every payload O(K_facts), none O(N):

    1. ``pmax`` — each chip scatters the current incarnations of the
       fact subjects living in ITS node shard into a u32[K] vector
       (zero elsewhere); the element-wise max assembles exactly the
       ``incarnation[subject]`` gather of the unsharded staleness gate
       (incarnations are unsigned; each subject lives on exactly one
       chip).
    2. ``psum`` (the fused sum leg) — the stage-1 integer partials
       (``swim.telemetry_counts``: alive count, per-fact coverage
       columns, per-fact believer counts — agreement's cells/hit are
       exact integer folds of these after the reduce) ride ONE
       i32[1 + 2K] psum.  Integer addition is associative, so the
       reduced vector is bit-equal to the global sums.
    3. ``psum`` — the false-DEAD count: stage 2 recomputes the
       (replicated) believed-subjects judgment from the reduced counts,
       each chip slices its own rows, ORs its tombstone shard, counts,
       and one scalar psum closes it.

    The float math (ratios) runs AFTER the reduces on integers every
    chip agrees on — that is the bit-identity argument, and
    tests/test_telemetry_collective.py pins it per round against the
    gathered row for both schedules × both stamp flavors × controller
    on/off.  ``accounting.telemetry_leg_traffic`` prices these legs at
    O(fields) bytes per chip per round (~0 vs the exchange's packet
    blocks) — the in-network-aggregation claim of ROADMAP item 4.

    Falls back to the gathered row (loud ``shard-fallback`` flight
    event) when the mesh does not divide ``n``, mirroring
    :func:`exchange_sharded`.

    ``with_cols`` mirrors ``round_telemetry(with_cols=True)``: the leg
    additionally returns the post-psum ``(colcnt i32[K], alive_cnt)``
    stage-1 operands — replicated (exactly global) after the fused sum
    leg, so they leave the shard_map under ``P()`` with no extra
    collective; the propagation observatory folds sentinel coverage
    from them.
    """
    from serf_tpu.models.failure import believed_subjects
    from serf_tpu.models.swim import (
        round_telemetry,
        telemetry_counts,
        telemetry_finish,
        telemetry_stretch,
    )
    from serf_tpu.parallel.mesh import partition_specs

    n = cfg.n
    d = mesh.shape[NODE_AXIS]
    if d > 1 and n % d != 0:
        from serf_tpu import obs
        obs.record("shard-fallback", op="round_telemetry_sharded", n=n,
                   devices=d, reason="n % devices != 0; gathered row")
        return round_telemetry(state, cfg, with_cols=with_cols)
    n_local = n // d
    g = state.gossip
    stretch = telemetry_stretch(state, cfg)
    has_stretch = stretch is not None
    k_facts = cfg.gossip.k_facts

    def leg(gs, *rest):
        st = rest[0] if has_stretch else None
        # leg 1 (pmax): assemble the subject-incarnation vector from
        # each chip's shard — the staleness gate's N-gather, made O(K)
        me = jax.lax.axis_index(NODE_AXIS)
        gstart = me * n_local
        subj = jnp.clip(gs.facts.subject, 0)
        local = subj - gstart
        mine = (local >= 0) & (local < n_local)
        contrib = jnp.where(
            mine, gs.incarnation[jnp.clip(local, 0, n_local - 1)],
            jnp.uint32(0))
        subj_inc = contrib if d == 1 \
            else jax.lax.pmax(contrib, NODE_AXIS)
        # leg 2 (fused psum): the stage-1 integer partials
        alive_cnt, colcnt, believers = telemetry_counts(
            gs, cfg, stretch_q=st, subj_inc=subj_inc)
        stage1 = jnp.concatenate(
            [alive_cnt[None], colcnt, believers])
        if d > 1:
            stage1 = jax.lax.psum(stage1, NODE_AXIS)
        alive_cnt = stage1[0]
        colcnt = stage1[1:1 + k_facts]
        believers = stage1[1 + k_facts:]
        # leg 3 (psum): believed-subjects is a pure function of the
        # replicated fact table + the reduced counts (every chip
        # computes the same bool[N]); each chip counts its own rows
        believed = believed_subjects(gs, n, believers, alive_cnt)
        rows = jax.lax.dynamic_slice_in_dim(believed, gstart, n_local)
        fd = jnp.sum((rows | gs.tombstone) & gs.alive)
        false_dead = fd if d == 1 else jax.lax.psum(fd, NODE_AXIS)
        row = telemetry_finish(gs, cfg, alive_cnt, colcnt, false_dead,
                               subj_inc=subj_inc)
        if with_cols:
            # post-psum: replicated, exactly the global stage-1 counts
            return row, colcnt, alive_cnt
        return row

    operands = [g]
    specs = [partition_specs(g)]
    if has_stretch:
        operands.append(jnp.asarray(stretch, jnp.int32))
        specs.append(P())
    # check_rep off: the leg mixes device-varying shards with values
    # provably replicated only through psum/pmax and the fact table —
    # the replication argument is the docstring's, pinned by the
    # bit-identity tests, not re-derivable by shard_map's checker
    out_specs = (P(), P(), P()) if with_cols else P()
    tele = shard_map(leg, mesh=mesh, in_specs=tuple(specs),
                     out_specs=out_specs, check_rep=False)
    return tele(*operands)


def sharded_round_step(state: GossipState, cfg: GossipConfig,
                       key: jax.Array, mesh, schedule: str = "ring",
                       group=None, drop_rate=None,
                       eff_fanout=None, stamp_unit=None,
                       collect_propagation: bool = False):
    """One gossip round with the explicit sharded exchange — bit-exact
    with ``round_step(state, cfg, key, group, drop_rate)`` by
    construction: it IS ``round_step`` (same select/merge/quiet-gate/
    cache/clamp code, one copy) with only the exchange leg swapped for
    :func:`exchange_sharded`.

    With ``cfg.use_pallas`` + ``cfg.fused_kernels`` the select/merge
    phases run the FUSED kernel family under shard_map per chip
    (``round_step(mesh=)`` threads it through) — the PR-6 restriction
    that forced the sharded round off the pallas path is gone.  The
    standalone (non-fused) kernels remain single-device; requesting
    them here falls back to the XLA phases with a loud
    ``pallas-fallback`` flight event (``dissemination._pallas_mode``).

    ``collect_propagation`` forwards the redundancy-ledger flag
    (``round_step``'s docstring): the ledger reductions run on the
    GSPMD-sharded global planes OUTSIDE the shard_map leg, where
    integer sums globalize exactly — same code, same bits, sharded or
    not."""
    return round_step(state, cfg, key, group=group, drop_rate=drop_rate,
                      exchange=functools.partial(exchange_sharded,
                                                 mesh=mesh,
                                                 schedule=schedule),
                      mesh=mesh, eff_fanout=eff_fanout,
                      stamp_unit=stamp_unit,
                      collect_propagation=collect_propagation)
