"""Multi-host (DCN) scaling for the cluster simulation.

The reference scales over real networks with NCCL-free gossip (sockets);
the device plane scales the *simulation* over pods: hosts connect with
``jax.distributed``, devices form a 2-D ``(dcn, ici)`` mesh, and the node
dimension shards over both axes.  Within a host, cross-shard gossip packets
ride ICI; across hosts, the same all-gather rides DCN.  Because the round
kernel only ever all-gathers the small packed packet words (N×W uint32 —
32 MB at 1M nodes), DCN bandwidth is not the bottleneck until far larger
clusters.

This module is exercised in CI only at the single-host virtual-device
level (the environment has one chip); the multi-host entry is the standard
``jax.distributed.initialize`` contract and is kept thin on purpose.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join the jax.distributed job.

    Called with no arguments, defers to ``jax.distributed.initialize()``'s
    pod auto-detection (the natural call on a real TPU slice).  Pass
    ``num_processes<=1`` explicitly to no-op for single-process runs.
    """
    if num_processes is not None and num_processes <= 1:
        return
    if (coordinator_address is None and num_processes is None
            and process_id is None):
        jax.distributed.initialize()  # TPU-pod auto-detection
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh() -> Mesh:
    """(dcn, ici) mesh: hosts on the outer axis, local devices inner.

    With one process this degenerates to ``(1, n_local_devices)``.
    """
    n_procs = jax.process_count()
    local = jax.local_device_count()
    devices = np.array(jax.devices()).reshape(n_procs, local)
    return Mesh(devices, (DCN_AXIS, ICI_AXIS))


def hybrid_node_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the node dimension across BOTH axes: nodes split first over
    hosts (DCN), then over local chips (ICI)."""
    return NamedSharding(mesh, P((DCN_AXIS, ICI_AXIS)))


def shard_cluster_hybrid(state, mesh: Mesh):
    """Place a ClusterState on the hybrid mesh (same rules as
    ``serf_tpu.parallel.mesh``: per-node arrays shard, facts replicate)."""
    from serf_tpu.parallel.mesh import NODE_AXIS, _spec_for

    node_sharding = hybrid_node_sharding(mesh)
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    out = []
    for path, leaf in flat:
        # the PATH TUPLE, not keystr(path): _spec_for dispatches on the
        # attribute names along the path, and a flattened string made
        # every rank>=1 leaf look name-less — fact planes were silently
        # node-sharded on the hybrid mesh (harmless only while every
        # leading dim happened to divide the device count; the 4-wide
        # control knob vector turned it into a hard error)
        spec = _spec_for(path, leaf)
        if spec == P(NODE_AXIS):
            sharding = node_sharding
        else:
            sharding = NamedSharding(mesh, spec)
        out.append(jax.device_put(leaf, sharding))
    return jax.tree_util.tree_unflatten(treedef, out)
