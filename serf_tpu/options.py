"""Configuration: all protocol knobs with reference-parity defaults.

Reference: serf-core/src/options.rs:495-530 (serf knobs) and the memberlist
tunables serf's tests exercise (serf-core/src/serf/base/tests.rs:25-39).
Durations are seconds (float) instead of the reference's humantime strings.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional

from serf_tpu.types.tags import Tags

# Hard caps (reference serf-core/src/serf.rs:40-44)
USER_EVENT_SIZE_LIMIT = 9 * 1024     # 9 KiB hard cap on encoded user events
SNAPSHOT_SIZE_LIMIT = 128 * 1024     # min snapshot compaction threshold


@dataclass(frozen=True)
class MemberlistOptions:
    """SWIM-layer tunables (reference memberlist LAN profile; SURVEY.md §2.9)."""

    bind_addr: object = None                 # transport-specific
    gossip_interval: float = 0.2             # LAN default 200ms
    gossip_nodes: int = 3                    # fan-out per gossip tick
    probe_interval: float = 1.0
    probe_timeout: float = 0.5
    indirect_checks: int = 3
    suspicion_mult: int = 4
    suspicion_max_timeout_mult: int = 6
    retransmit_mult: int = 4
    push_pull_interval: float = 30.0
    awareness_max_multiplier: int = 8        # Lifeguard local-health ceiling
    timeout: float = 10.0                    # stream (push/pull) op timeout
    compression: Optional[str] = None        # None | zlib/lz4/snappy/zstd
    checksum: Optional[str] = None           # None | crc32/adler32/xxhash32/murmur3
    metric_labels: Dict[str, str] = field(default_factory=dict)

    def validate(self) -> None:
        from serf_tpu.host.wire import CHECKSUMS, compression_available
        if self.compression is not None and not compression_available(
                self.compression):
            raise ValueError(f"unsupported compression {self.compression!r}")
        if self.checksum is not None and self.checksum not in CHECKSUMS:
            raise ValueError(f"unsupported checksum {self.checksum!r}")

    @classmethod
    def lan(cls) -> "MemberlistOptions":
        return cls()

    @classmethod
    def in_process(cls, n: int) -> "MemberlistOptions":
        """Timings for LARGE in-process clusters sharing one event loop.

        The compressed ``local()`` profile collapses past ~32 co-located
        nodes: scheduling lag makes 25 ms probe timeouts fail en masse and
        1x-suspicion expires before refutations land, mass-killing healthy
        nodes.  This profile keeps gossip fast but scales the failure
        detector with cluster size: event-loop lag grows with the number of
        co-located nodes, so probe timings stretch ~sqrt(n/64) (suspicion
        already scales log10(n) through suspicion_mult).
        """
        f = max(1.0, (n / 64.0) ** 0.5)
        return cls(
            gossip_interval=0.02,
            probe_interval=0.4 * f,
            probe_timeout=0.15 * f,
            suspicion_mult=4,
            push_pull_interval=2.0,
            timeout=5.0,
        )

    @classmethod
    def local(cls) -> "MemberlistOptions":
        """Compressed timings for in-process tests (reference base/tests.rs:25-39)."""
        return cls(
            gossip_interval=0.005,
            probe_interval=0.05,
            probe_timeout=0.025,
            suspicion_mult=1,
            push_pull_interval=1.0,  # anti-entropy repair net for tests;
                                     # hotter rates saturate big in-process
                                     # clusters (every sync is O(N) decode)
            timeout=2.0,
        )


@dataclass(frozen=True)
class Options:
    """Serf-layer knobs, defaults matching reference options.rs:495-530."""

    broadcast_timeout: float = 5.0
    leave_propagate_delay: float = 1.0
    coalesce_period: float = 0.0          # 0 = coalescing off
    quiescent_period: float = 0.0
    user_coalesce_period: float = 0.0
    user_quiescent_period: float = 0.0
    reap_interval: float = 15.0
    reconnect_interval: float = 30.0
    reconnect_timeout: float = 24 * 3600.0
    tombstone_timeout: float = 24 * 3600.0
    flap_timeout: float = 60.0
    queue_check_interval: float = 30.0
    queue_depth_warning: int = 128
    max_queue_depth: int = 4096
    min_queue_depth: int = 0
    recent_intent_timeout: float = 300.0
    event_buffer_size: int = 512
    query_buffer_size: int = 512
    query_timeout_mult: int = 16
    query_size_limit: int = 1024
    query_response_size_limit: int = 1024
    memberlist: MemberlistOptions = field(default_factory=MemberlistOptions.lan)
    snapshot_path: Optional[str] = None
    snapshot_min_compact_size: int = SNAPSHOT_SIZE_LIMIT
    rejoin_after_leave: bool = False
    enable_id_conflict_resolution: bool = True
    disable_coordinates: bool = False
    tags: Tags = field(default_factory=Tags)
    max_user_event_size: int = 512
    keyring_file: Optional[str] = None

    def replace(self, **kw) -> "Options":
        return replace(self, **kw)

    def validate(self) -> None:
        if self.max_user_event_size > USER_EVENT_SIZE_LIMIT:
            raise ValueError(
                f"max_user_event_size {self.max_user_event_size} exceeds hard cap "
                f"{USER_EVENT_SIZE_LIMIT}"
            )
        self.memberlist.validate()

    @classmethod
    def local(cls, **kw) -> "Options":
        """Test profile: compressed timers (reference base/tests.rs:25-39)."""
        defaults = dict(
            memberlist=MemberlistOptions.local(),
            reap_interval=1.0,
            reconnect_interval=1.0,
            recent_intent_timeout=5.0,
            queue_check_interval=1.0,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def cluster(cls, n: int, **kw) -> "Options":
        """Profile for large in-process clusters (see
        MemberlistOptions.in_process)."""
        defaults = dict(
            memberlist=MemberlistOptions.in_process(n),
            reap_interval=5.0,
            reconnect_interval=5.0,
        )
        defaults.update(kw)
        return cls(**defaults)
