"""Configuration: all protocol knobs with reference-parity defaults.

Reference: serf-core/src/options.rs:495-530 (serf knobs) and the memberlist
tunables serf's tests exercise (serf-core/src/serf/base/tests.rs:25-39).
Durations are seconds (float) in code; the serde layer (``Options.to_json/
from_json/to_toml/from_toml``) reads and writes humantime strings
("24h", "500ms", "1h30m") exactly like the reference's serde feature
(options.rs:55, 567-590).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field, fields, replace
from typing import Any, Dict, Optional

from serf_tpu.types.tags import Tags

# ---------------------------------------------------------------------------
# humantime durations (reference options.rs:55 `serde(with = humantime)`)
# ---------------------------------------------------------------------------

_UNIT_SECONDS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
}
_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)\s*(ns|us|µs|ms|s|m|h|d)")


def parse_duration(value) -> float:
    """Humantime-style duration → seconds.  Accepts plain numbers
    (seconds) or strings like "500ms", "24h", "1h30m", "2.5s"."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        # NaN passes a bare `< 0` check (comparisons are False) and inf
        # round-trips into format_duration's OverflowError — reject both
        if not math.isfinite(value) or value < 0:
            raise ValueError(f"non-finite or negative duration {value!r}")
        return float(value)
    if not isinstance(value, str):
        raise ValueError(f"cannot parse duration from {value!r}")
    s = value.strip()
    if not s:
        raise ValueError("empty duration")
    try:
        return parse_duration(float(s))      # bare "5" / "0.25" = seconds
    except ValueError:
        pass
    pos, total = 0, 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            break
        total += float(m.group(1)) * _UNIT_SECONDS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {value!r}")
    return total


def format_duration(seconds: float) -> str:
    """Seconds → compact humantime string ("24h", "1h30m", "500ms")."""
    if not math.isfinite(seconds) or seconds < 0:
        raise ValueError(f"non-finite or negative duration {seconds!r}")
    if seconds == 0:
        return "0s"
    ns = round(seconds * 1e9)
    parts = []
    for unit, mult in (("d", 86400_000_000_000), ("h", 3600_000_000_000),
                       ("m", 60_000_000_000), ("s", 1_000_000_000),
                       ("ms", 1_000_000), ("us", 1_000), ("ns", 1)):
        q, ns = divmod(ns, mult)
        if q:
            parts.append(f"{q}{unit}")
    return "".join(parts) or "0s"

# Hard caps (reference serf-core/src/serf.rs:40-44)
USER_EVENT_SIZE_LIMIT = 9 * 1024     # 9 KiB hard cap on encoded user events
SNAPSHOT_SIZE_LIMIT = 128 * 1024     # min snapshot compaction threshold


@dataclass(frozen=True)
class MemberlistOptions:
    """SWIM-layer tunables (reference memberlist LAN profile; SURVEY.md §2.9)."""

    bind_addr: object = None                 # transport-specific
    gossip_interval: float = 0.2             # LAN default 200ms
    gossip_nodes: int = 3                    # fan-out per gossip tick
    probe_interval: float = 1.0
    probe_timeout: float = 0.5
    indirect_checks: int = 3
    suspicion_mult: int = 4
    suspicion_max_timeout_mult: int = 6
    retransmit_mult: int = 4
    push_pull_interval: float = 30.0
    awareness_max_multiplier: int = 8        # Lifeguard local-health ceiling
    timeout: float = 10.0                    # stream (push/pull) op timeout
    compression: Optional[str] = None        # None | zlib/lz4/snappy/zstd
    checksum: Optional[str] = None           # None | crc32/adler32/xxhash32/murmur3
    protocol_version: int = 1                # advertised on the wire (vsn)
    delegate_version: int = 1                # reference version.rs:9-43
    # graceful degradation (host/degrade.py): stream dials and push/pull
    # retry with jittered exponential backoff; a peer failing
    # breaker_threshold consecutive times opens a circuit that fast-fails
    # further attempts for breaker_cooldown (then one half-open trial)
    dial_backoff_base: float = 0.05          # first-retry backoff (jittered)
    dial_backoff_max: float = 1.0            # backoff growth cap
    dial_retries: int = 2                    # extra dial attempts per op
    join_retries: int = 2                    # extra join (push/pull) attempts
    breaker_threshold: int = 4               # consecutive failures to open
    breaker_cooldown: float = 2.0            # open-circuit fast-fail window
    # overload protection (host/admission.py): per-peer USER-plane send
    # pacing at the Memberlist.send seam — at most peer_send_rate
    # packets/sec (burst peer_send_burst) to any single destination;
    # excess is DROPPED (loss-based pacing; gossip is redundant).  The
    # SWIM probe/ack/gossip plane is never paced.  0 = disabled.
    peer_send_rate: float = 0.0
    peer_send_burst: int = 64
    # encrypted gossip fan-out (ISSUE 20): seal the per-tick gossip
    # payload ONCE and send the same ciphertext to all k targets (one
    # AEAD call instead of k); False restores per-packet encryption —
    # the bench encryption_ab A/B flips this knob
    gossip_encrypt_amortize: bool = True
    metric_labels: Dict[str, str] = field(default_factory=dict)

    def validate(self) -> None:
        from serf_tpu.host.wire import CHECKSUMS, compression_available
        from serf_tpu.host.messages import (
            PROTOCOL_VERSION_MIN, PROTOCOL_VERSION_MAX,
            DELEGATE_VERSION_MIN, DELEGATE_VERSION_MAX)
        if self.compression is not None and not compression_available(
                self.compression):
            raise ValueError(f"unsupported compression {self.compression!r}")
        if self.checksum is not None and self.checksum not in CHECKSUMS:
            raise ValueError(f"unsupported checksum {self.checksum!r}")
        if not (PROTOCOL_VERSION_MIN <= self.protocol_version
                <= PROTOCOL_VERSION_MAX):
            raise ValueError(
                f"protocol_version {self.protocol_version} outside supported "
                f"[{PROTOCOL_VERSION_MIN}, {PROTOCOL_VERSION_MAX}]")
        if not (DELEGATE_VERSION_MIN <= self.delegate_version
                <= DELEGATE_VERSION_MAX):
            raise ValueError(
                f"delegate_version {self.delegate_version} outside supported "
                f"[{DELEGATE_VERSION_MIN}, {DELEGATE_VERSION_MAX}]")
        if self.dial_backoff_base <= 0 or self.dial_backoff_max <= 0:
            raise ValueError("dial backoff durations must be positive")
        if self.dial_retries < 0 or self.join_retries < 0:
            raise ValueError("retry counts must be >= 0")
        if self.breaker_threshold < 1 or self.breaker_cooldown < 0:
            raise ValueError("breaker_threshold >= 1 and "
                             "breaker_cooldown >= 0 required")
        if self.peer_send_rate < 0:
            raise ValueError("peer_send_rate must be >= 0 (0 = disabled)")
        if self.peer_send_burst < 1:
            raise ValueError("peer_send_burst must be >= 1")

    @classmethod
    def lan(cls) -> "MemberlistOptions":
        return cls()

    @classmethod
    def in_process(cls, n: int) -> "MemberlistOptions":
        """Timings for LARGE in-process clusters sharing one event loop.

        The compressed ``local()`` profile collapses past ~32 co-located
        nodes: scheduling lag makes 25 ms probe timeouts fail en masse and
        1x-suspicion expires before refutations land, mass-killing healthy
        nodes.  This profile keeps gossip fast but scales the failure
        detector with cluster size: event-loop lag grows with the number of
        co-located nodes, so probe timings stretch ~sqrt(n/64) (suspicion
        already scales log10(n) through suspicion_mult).
        """
        f = max(1.0, (n / 64.0) ** 0.5)
        return cls(
            gossip_interval=0.02,
            probe_interval=0.4 * f,
            probe_timeout=0.15 * f,
            suspicion_mult=4,
            push_pull_interval=2.0,
            timeout=5.0,
        )

    @classmethod
    def proc(cls) -> "MemberlistOptions":
        """Timings for MULTI-PROCESS loopback clusters (ISSUE 19): each
        node owns its event loop, so probes tolerate interpreter startup
        and scheduler jitter rather than co-located loop lag.  Push/pull
        runs hot (0.5s) so a kill window reliably catches an anti-entropy
        sync mid-flight, and the breaker opens after 2 consecutive
        failures so a SIGKILLed peer shows up in the survivors'
        ``serf.degraded.*`` counters within one chaos phase."""
        return cls(
            gossip_interval=0.02,
            probe_interval=0.2,
            probe_timeout=0.1,
            suspicion_mult=3,
            push_pull_interval=0.5,
            timeout=2.0,
            dial_backoff_base=0.02,
            dial_backoff_max=0.2,
            breaker_threshold=2,
            breaker_cooldown=0.5,
        )

    @classmethod
    def local(cls) -> "MemberlistOptions":
        """Compressed timings for in-process tests (reference base/tests.rs:25-39)."""
        return cls(
            gossip_interval=0.005,
            probe_interval=0.05,
            probe_timeout=0.025,
            suspicion_mult=1,
            push_pull_interval=1.0,  # anti-entropy repair net for tests;
                                     # hotter rates saturate big in-process
                                     # clusters (every sync is O(N) decode)
            timeout=2.0,
            dial_backoff_base=0.01,
            dial_backoff_max=0.08,
            breaker_cooldown=0.25,
        )


@dataclass(frozen=True)
class Options:
    """Serf-layer knobs, defaults matching reference options.rs:495-530."""

    broadcast_timeout: float = 5.0
    leave_propagate_delay: float = 1.0
    coalesce_period: float = 0.0          # 0 = coalescing off
    quiescent_period: float = 0.0
    user_coalesce_period: float = 0.0
    user_quiescent_period: float = 0.0
    reap_interval: float = 15.0
    reconnect_interval: float = 30.0
    reconnect_timeout: float = 24 * 3600.0
    tombstone_timeout: float = 24 * 3600.0
    flap_timeout: float = 60.0
    queue_check_interval: float = 30.0
    health_interval: float = 5.0          # health-score / loop-lag monitor
    queue_depth_warning: int = 128
    max_queue_depth: int = 4096
    min_queue_depth: int = 0
    recent_intent_timeout: float = 300.0
    event_buffer_size: int = 512
    query_buffer_size: int = 512
    query_timeout_mult: int = 16
    query_size_limit: int = 1024
    query_response_size_limit: int = 1024
    # ---- overload protection (ISSUE 5) ------------------------------------
    # Byte budgets per broadcast queue (0 = unbounded).  Shedding priority
    # (host/broadcast.py): SWIM membership facts are NEVER shed; intents
    # get the largest budget, user events less, query fan-out least.
    intent_queue_bytes: int = 8 * 1024 * 1024
    event_queue_bytes: int = 4 * 1024 * 1024
    query_queue_bytes: int = 2 * 1024 * 1024
    #: bound on live originator-side query handlers (_query_responses);
    #: at capacity the entry closest to its deadline is evicted (counted)
    max_query_responses: int = 1024
    #: cadence of the single periodic sweep that reclaims expired query
    #: handlers (replaces the per-query expiry task — a query storm must
    #: not be a task storm)
    query_sweep_interval: float = 1.0
    #: bound on the protocol->pipeline event intake; non-membership events
    #: beyond it are shed (member events are membership state: never shed)
    event_inbox_max: int = 8192
    #: applier workers draining the MPMC event pipeline (host/pipeline.py):
    #: per-dependency-key serial, cross-key parallel application
    pipeline_workers: int = 4
    #: ingress token buckets (host/admission.py); rate 0 = unlimited
    user_event_rate: float = 0.0
    user_event_burst: int = 64
    query_rate: float = 0.0
    query_burst: int = 32
    #: per-tenant fairness buckets keyed by event/query NAME CLASS
    #: (host/pipeline.name_class): one noisy tenant drains its own
    #: bucket, not the cluster's; rate 0 = disabled
    tenant_event_rate: float = 0.0
    tenant_event_burst: int = 32
    tenant_query_rate: float = 0.0
    tenant_query_burst: int = 16
    #: health floor: when the obs.health score drops below this, user
    #: ingress is shed and inbound user queries are fast-failed with an
    #: explicit OVERLOADED response (0 = disabled)
    admission_min_health: int = 0
    memberlist: MemberlistOptions = field(default_factory=MemberlistOptions.lan)
    snapshot_path: Optional[str] = None
    snapshot_min_compact_size: int = SNAPSHOT_SIZE_LIMIT
    rejoin_after_leave: bool = False
    enable_id_conflict_resolution: bool = True
    disable_coordinates: bool = False
    tags: Tags = field(default_factory=Tags)
    max_user_event_size: int = 512
    keyring_file: Optional[str] = None

    def replace(self, **kw) -> "Options":
        return replace(self, **kw)

    def validate(self) -> None:
        if self.max_user_event_size > USER_EVENT_SIZE_LIMIT:
            raise ValueError(
                f"max_user_event_size {self.max_user_event_size} exceeds hard cap "
                f"{USER_EVENT_SIZE_LIMIT}"
            )
        for name in ("intent_queue_bytes", "event_queue_bytes",
                     "query_queue_bytes", "event_inbox_max"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0 (0 = unbounded)")
        if self.max_query_responses < 1:
            raise ValueError("max_query_responses must be >= 1")
        if self.query_sweep_interval <= 0:
            raise ValueError("query_sweep_interval must be positive")
        if self.user_event_rate < 0 or self.query_rate < 0 \
                or self.tenant_event_rate < 0 or self.tenant_query_rate < 0:
            raise ValueError("ingress rates must be >= 0 (0 = unlimited)")
        if self.user_event_burst < 1 or self.query_burst < 1 \
                or self.tenant_event_burst < 1 or self.tenant_query_burst < 1:
            raise ValueError("ingress bursts must be >= 1")
        if self.pipeline_workers < 1:
            raise ValueError("pipeline_workers must be >= 1")
        if not 0 <= self.admission_min_health <= 100:
            raise ValueError("admission_min_health must be in [0, 100]")
        self.memberlist.validate()

    @classmethod
    def local(cls, **kw) -> "Options":
        """Test profile: compressed timers (reference base/tests.rs:25-39)."""
        defaults = dict(
            memberlist=MemberlistOptions.local(),
            reap_interval=1.0,
            reconnect_interval=1.0,
            recent_intent_timeout=5.0,
            queue_check_interval=1.0,
            health_interval=0.25,
            query_sweep_interval=0.1,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def proc(cls, **kw) -> "Options":
        """Profile for multi-process loopback clusters (the serf agent's
        default; see MemberlistOptions.proc)."""
        defaults = dict(
            memberlist=MemberlistOptions.proc(),
            reap_interval=2.0,
            reconnect_interval=1.0,
            recent_intent_timeout=10.0,
            queue_check_interval=1.0,
            health_interval=0.25,
            query_sweep_interval=0.2,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def cluster(cls, n: int, **kw) -> "Options":
        """Profile for large in-process clusters (see
        MemberlistOptions.in_process)."""
        defaults = dict(
            memberlist=MemberlistOptions.in_process(n),
            reap_interval=5.0,
            reconnect_interval=5.0,
        )
        defaults.update(kw)
        return cls(**defaults)

    # -- serde (reference options.rs:55, 567-590: serde + humantime) -------

    def to_dict(self) -> Dict[str, Any]:
        """Plain-data form: durations as humantime strings, tags/labels as
        string maps.  Round-trips through ``from_dict``."""
        out: Dict[str, Any] = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if f.name == "memberlist":
                out[f.name] = _ml_to_dict(v)
            elif f.name == "tags":
                out[f.name] = dict(v)
            elif f.name in _OPTIONS_DURATIONS:
                out[f.name] = format_duration(v)
            else:
                out[f.name] = v
        return out

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Options":
        """Inverse of ``to_dict``; duration fields also accept plain
        numbers (seconds).  Unknown keys fail loudly."""
        kw: Dict[str, Any] = {}
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown Options keys: {sorted(unknown)}")
        for name, v in data.items():
            if name == "memberlist":
                kw[name] = _ml_from_dict(v)
            elif name == "tags":
                kw[name] = Tags(**v) if isinstance(v, dict) else v
            elif name in _OPTIONS_DURATIONS:
                kw[name] = parse_duration(v)
            else:
                kw[name] = v
        return cls(**kw)

    def to_json(self, **json_kw) -> str:
        import json
        json_kw.setdefault("indent", 2)
        return json.dumps(self.to_dict(), **json_kw)

    @classmethod
    def from_json(cls, text: str) -> "Options":
        import json
        return cls.from_dict(json.loads(text))

    def to_toml(self) -> str:
        """Minimal TOML emitter for the two-level Options shape (stdlib has
        a TOML reader, ``tomllib``, but no writer)."""
        top = self.to_dict()
        ml = top.pop("memberlist")
        tags = top.pop("tags")
        lines = [_toml_kv(k, v) for k, v in top.items() if v is not None]
        if tags:
            lines += ["", "[tags]"] + [_toml_kv(k, v) for k, v in tags.items()]
        lines += ["", "[memberlist]"]
        labels = ml.pop("metric_labels", {})
        lines += [_toml_kv(k, v) for k, v in ml.items() if v is not None]
        if labels:
            lines += ["", "[memberlist.metric_labels]"]
            lines += [_toml_kv(k, v) for k, v in labels.items()]
        return "\n".join(lines) + "\n"

    @classmethod
    def from_toml(cls, text: str) -> "Options":
        import tomllib
        return cls.from_dict(tomllib.loads(text))


#: fields (de)serialized as humantime duration strings
_OPTIONS_DURATIONS = frozenset({
    "broadcast_timeout", "leave_propagate_delay", "coalesce_period",
    "quiescent_period", "user_coalesce_period", "user_quiescent_period",
    "reap_interval", "reconnect_interval", "reconnect_timeout",
    "tombstone_timeout", "flap_timeout", "queue_check_interval",
    "health_interval", "recent_intent_timeout", "query_sweep_interval",
})
_ML_DURATIONS = frozenset({
    "gossip_interval", "probe_interval", "probe_timeout",
    "push_pull_interval", "timeout",
    "dial_backoff_base", "dial_backoff_max", "breaker_cooldown",
})


def _ml_to_dict(ml: MemberlistOptions) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for f in fields(ml):
        v = getattr(ml, f.name)
        if f.name in _ML_DURATIONS:
            out[f.name] = format_duration(v)
        elif f.name == "metric_labels":
            out[f.name] = dict(v)
        else:
            out[f.name] = v
    return out


def _ml_from_dict(data) -> MemberlistOptions:
    if isinstance(data, MemberlistOptions):
        return data
    known = {f.name for f in fields(MemberlistOptions)}
    unknown = set(data) - known
    if unknown:
        raise ValueError(f"unknown MemberlistOptions keys: {sorted(unknown)}")
    kw = {name: parse_duration(v) if name in _ML_DURATIONS else v
          for name, v in data.items()}
    return MemberlistOptions(**kw)


def _toml_kv(key: str, v: Any) -> str:
    if isinstance(v, bool):
        return f"{key} = {'true' if v else 'false'}"
    if isinstance(v, (int, float)):
        return f"{key} = {v}"
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'{key} = "{escaped}"'
    raise ValueError(f"cannot TOML-encode {key}={v!r}")
