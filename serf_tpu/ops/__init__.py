"""Device kernels: Pallas fast paths for the gossip round.

Two families (``round_kernels``): the standalone per-phase kernels
(``select_packets``/``merge_incoming``) and the fused-round family
(``fused_select_cached``/``fused_merge``) that maintains the sendable
cache in-kernel and runs under shard_map on the sharded flagship path.
Dispatch is selected by ``GossipConfig.use_pallas`` +
``GossipConfig.fused_kernels`` and gated by ``fused_ok`` (shape + VMEM
working-set estimate; rejections record a ``pallas-fallback`` flight
event and bump the ``serf.pallas.fused_fallback`` counter).

Kernel dispatch timers ride the shared obs compile-vs-steady split
(``serf_tpu.obs.device.dispatch_timer``) under ``ops.*`` op names — a
host wall clock, never an extra ``jax.device_get``; the bench's
``dispatch`` section enumerates whatever ops registered, so there is no
name list here to drift.
"""

from serf_tpu.ops.round_kernels import (
    VMEM_BUDGET_BYTES,
    fused_flush,
    fused_merge,
    fused_ok,
    fused_select_cached,
    fused_vmem_bytes,
    merge_incoming,
    pallas_ok,
    select_packets,
)

__all__ = [
    "VMEM_BUDGET_BYTES", "fused_flush", "fused_merge", "fused_ok",
    "fused_select_cached", "fused_vmem_bytes", "merge_incoming",
    "pallas_ok", "select_packets",
]
