"""Device kernels: Pallas fused fast paths for the gossip round
(``round_kernels``; enabled via ``GossipConfig.use_pallas``)."""
