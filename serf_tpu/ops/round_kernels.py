"""Pallas TPU kernels for the gossip round's elementwise phases.

The round kernel (serf_tpu/models/dissemination.py) has three phases:

1. packet selection: pack ``known & (derived age < transmit_limit) &
   alive`` into uint32 words (a fact's age derives from its learn-round
   stamp — see ``GossipState``; nothing ticks),
2. pull-exchange: peer read + OR-reduce (left to XLA — rolls/gathers are
   already bandwidth-optimal and fuse with the RNG),
3. merge: learn new facts (bit ops over N×W) and stamp them with the
   post-increment round (N×K) — a fresh stamp is a fresh budget.

Phases 1 and 3 each touch the N×K uint8 stamp plane plus the N×W word
plane; under plain XLA they materialize several N×K intermediates (the
sending mask, the unpacked known/new-fact masks).  These kernels fuse each
phase into a single pass: one read and one write per array, everything
else in VMEM registers.  The XLA path in ``dissemination.py`` remains the
semantic oracle; parity is pinned by tests (interpret mode on CPU,
compiled on TPU).

Layout notes (pallas_guide.md): blocks are (BLOCK_N, K) uint8 / (BLOCK_N, W)
uint32 in VMEM; scalars ride SMEM as (1, 1); iota is 2-D broadcasted_iota;
unpacking uses a static repeat + per-lane shift, no gathers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from serf_tpu.obs.device import dispatch_timer


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _block_for(n: int) -> int:
    """Largest supported node-block size dividing N."""
    for b in (512, 256, 128, 64, 32):
        if n % b == 0:
            return b
    return 0


def pallas_ok(n: int, k_facts: int) -> bool:
    """Shapes the kernels support: a node block divides N, K is a multiple
    of 32 (the word size)."""
    return _block_for(n) > 0 and k_facts % 32 == 0


def _unpack_words(words: jnp.ndarray, k: int) -> jnp.ndarray:
    """(B, W) u32 -> (B, K) bool via static repeat + per-lane shift (no
    gathers; pltpu.repeat tiles, so repeat a 1-wide slice per word)."""
    w = words.shape[1]
    groups = [pltpu.repeat(words[:, wi:wi + 1], 32, axis=1)
              for wi in range(w)]
    repeated = jnp.concatenate(groups, axis=1)                 # (B, K)
    shifts = (jax.lax.broadcasted_iota(jnp.uint32, (1, k), 1) % 32)
    return ((repeated >> shifts) & 1).astype(bool)


def _pack_bits(mask: jnp.ndarray, k: int) -> jnp.ndarray:
    """(B, K) bool -> (B, W) u32.  Mosaic has no unsigned reductions; sum
    in int32 and bitcast.  Each weight 1<<j appears at most once per word,
    so the signed sum is any 32-bit pattern reinterpreted — always
    representable, never overflows."""
    w = k // 32
    bits = mask.astype(jnp.int32)
    weights = (jnp.int32(1) << (
        jax.lax.broadcasted_iota(jnp.int32, (1, k), 1) % 32))
    weighted = bits * weights                      # (B, K)
    words = []
    for wi in range(w):
        words.append(jnp.sum(weighted[:, wi * 32:(wi + 1) * 32], axis=1,
                             keepdims=True, dtype=jnp.int32))
    return jax.lax.bitcast_convert_type(
        jnp.concatenate(words, axis=1), jnp.uint32)


# ---------------------------------------------------------------------------
# phase 1: packet selection
# ---------------------------------------------------------------------------


def _select_kernel(limit_ref, round_ref, stamp_ref, known_ref, alive_ref,
                   packets_ref):
    stamp = stamp_ref[:]                           # (B, K) u8
    known = known_ref[:]                           # (B, W) u32
    alive = alive_ref[:]                           # (B, 1) u8
    k = stamp.shape[1]
    limit = limit_ref[0, 0]                        # i32
    rnd = round_ref[0, 0]                          # i32
    # derived age in i32 (mod-256 wrap): valid only where the known bit is
    # set — the AND below gates it
    age = (rnd - stamp.astype(jnp.int32)) & 0xFF   # (B, K)
    known_bits = _unpack_words(known, k)           # (B, K) bool
    sending = known_bits & (age < limit) & (alive > 0)
    packets_ref[:] = _pack_bits(sending, k)


def select_packets(stamp: jnp.ndarray, known: jnp.ndarray,
                   alive_u8: jnp.ndarray, limit: int, round_
                   ) -> jnp.ndarray:
    """packets u32[N,W]: one read-only pass over the stamp plane + known
    words (ages derive from stamps; nothing is ticked anywhere)."""
    n, k = stamp.shape
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    limit_arr = jnp.asarray(limit, jnp.int32).reshape(1, 1)
    round_arr = (jnp.asarray(round_, jnp.int32) & 0xFF).reshape(1, 1)
    # host wall clock only: eager calls time a real dispatch (first call
    # at a shape = compile), calls inside an outer jit time the trace
    with dispatch_timer("ops.select_packets", signature=(n, k)):
        return pl.pallas_call(
            _select_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n, w), jnp.uint32),
            interpret=_interpret(),
        )(limit_arr, round_arr, stamp, known, alive_u8)


# ---------------------------------------------------------------------------
# phase 3: merge incoming
# ---------------------------------------------------------------------------


def _merge_kernel(round_ref, known_ref, incoming_ref, alive_ref, stamp_ref,
                  known_out_ref, stamp_out_ref):
    known = known_ref[:]                           # (B, W) u32
    incoming = incoming_ref[:]                     # (B, W) u32
    alive = alive_ref[:]                           # (B, 1) u8
    stamp = stamp_ref[:]                           # (B, K) u8
    k = stamp.shape[1]
    alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    new_words = incoming & ~known & alive_words    # (B, W)
    known_out_ref[:] = known | new_words
    new_mask = _unpack_words(new_words, k)         # (B, K) bool
    r8 = round_ref[0, 0].astype(jnp.uint8)
    stamp_out_ref[:] = jnp.where(new_mask, r8, stamp)


def merge_incoming(known: jnp.ndarray, incoming: jnp.ndarray,
                   alive_u8: jnp.ndarray, stamp: jnp.ndarray, next_round
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(known', stamp') in one fused pass: learn new facts and stamp them
    with ``next_round`` (the post-increment round — first visible at
    derived age 0 in the next round's selection)."""
    n, k = stamp.shape
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    round_arr = (jnp.asarray(next_round, jnp.int32) & 0xFF).reshape(1, 1)
    with dispatch_timer("ops.merge_incoming", signature=(n, k)):
        return pl.pallas_call(
            _merge_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n, w), jnp.uint32),
                jax.ShapeDtypeStruct((n, k), jnp.uint8),
            ],
            interpret=_interpret(),
        )(round_arr, known, incoming, alive_u8, stamp)
