"""Pallas TPU kernels for the gossip round's elementwise phases.

The round kernel (serf_tpu/models/dissemination.py) has three phases:

1. packet selection: pack ``known & (derived q-age < transmit_limit_q) &
   alive`` into uint32 words (a fact's age derives from its 4-bit
   learn-quarter stamp — see ``GossipState``; nothing ticks),
2. pull-exchange: peer read + OR-reduce (left to XLA — rolls/gathers are
   already bandwidth-optimal and fuse with the RNG; this is also the one
   cross-chip leg, so it stays a separate hookable leg for the sharded
   flagship, ``parallel.ring.exchange_sharded``),
3. merge: learn new facts (bit ops over N×W), stamp them with the
   post-increment round's quarter, and re-pin wrap-stale stamps
   (``clamp_nibbles`` folded in — a fresh stamp is a fresh budget, and
   the standalone clamp pass never needs to fire after a merge).

Two kernel families live here:

- the PR-3 **standalone** kernels (``select_packets``/``merge_incoming``):
  each phase fused into one pass, but the merge does NOT maintain the
  sendable cache, so the pallas path used to invalidate it and every
  selection re-read the full stamp plane.
- the **fused-round** family (``fused_select_cached``/``fused_merge``,
  this PR): the merge kernel recomputes the sendable cache for round+1
  in the SAME streaming pass (the in-kernel analog of
  ``dissemination.learn_stamp_pass``), so the next round's selection is
  a word-plane-only kernel and the packed stamp plane is streamed
  exactly ONCE per round (the merge's R+W) instead of once per phase.
  Both kernels take an optional ``mesh`` and then run under
  ``shard_map`` over the node axis — each chip streams its N/P block —
  which is what lets the 8-chip sharded flagship round keep the pallas
  fast path (the PR-6 round had to disable it).  Dispatch is gated by
  :func:`fused_ok`: shape limits plus a VMEM working-set estimate so
  big-K configs fall back loudly instead of OOMing.

Phases 1 and 3 each touch the stamp plane (u8[N, K/2] nibble-packed by
default, u8[N, K] for the unpacked A/B flavor) plus the N×W word plane;
under plain XLA they materialize several N×K intermediates (the sending
mask, the unpacked known/new-fact masks).  These kernels fuse each phase
into a single pass: one read and one write per array, everything else in
VMEM registers.  The packed flavor never widens to K lanes at all: both
nibbles' age predicates are evaluated per BYTE column and woven straight
into u32 words (fact ``2c+p`` of byte ``c`` is bit ``2*(c%16)+p`` of
word ``c//16``), so selection is pure word-plane arithmetic.  The XLA
path in ``dissemination.py`` remains the semantic oracle; parity is
pinned by tests (interpret mode on CPU, compiled on TPU) — the fused
family is BIT-EXACT with the XLA path on every GossipState leaf
(tests/test_fused_round.py), cache included.

Layout notes (pallas_guide.md): blocks are (BLOCK_N, C) uint8 / (BLOCK_N,
W) uint32 in VMEM; scalars ride SMEM as (1, 1); iota is 2-D
broadcasted_iota; unpacking uses a static repeat + per-lane shift, no
gathers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from serf_tpu.obs.device import dispatch_timer


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _block_for(n: int) -> int:
    """Largest supported node-block size dividing N."""
    for b in (512, 256, 128, 64, 32):
        if n % b == 0:
            return b
    return 0


def pallas_ok(n: int, k_facts: int) -> bool:
    """Shapes the STANDALONE kernels support: a node block divides N, K a
    multiple of 32 (the word size — which also keeps the nibble-packed
    plane at a whole number of 16-byte word groups).  Single-device only
    (a ``pallas_call`` grid over the full N axis is not GSPMD-
    partitionable); the fused family's :func:`fused_ok` supersedes this
    with a VMEM working-set gate and shard_map support."""
    return _block_for(n) > 0 and k_facts % 32 == 0


# ---------------------------------------------------------------------------
# fused-family dispatch gate: shapes + VMEM working set
# ---------------------------------------------------------------------------

#: VMEM budget for one grid step's resident working set (v5e has ~16 MB
#: of VMEM per core; leave headroom for Mosaic's own scratch and the
#: compute intermediates the estimate cannot see)
VMEM_BUDGET_BYTES = 12 << 20


def fused_vmem_bytes(block_n: int, k_facts: int, stamp_cols: int,
                     deferred: bool = False) -> int:
    """Worst-case VMEM resident set of one fused-merge grid step: the
    known/incoming/known'/sendable' u32 blocks, the stamp block in and
    out, and the alive column — times 2 for the double-buffered DMA
    windows the pipelined grid keeps in flight.  The select kernels'
    sets are strict subsets, so one estimate gates the family.
    ``deferred`` grows the set by the flush kernel's overlay term (the
    overlay block streams in beside the stamp block on flush rounds —
    see :func:`fused_flush`)."""
    w = k_facts // 32
    per_row = 4 * 4 * w + 2 * stamp_cols + 1
    if deferred:
        per_row += 4 * w
    return 2 * block_n * per_row


def _fused_block(n: int, k_facts: int, stamp_cols: int,
                 deferred: bool = False) -> int:
    """Largest node block dividing N whose fused working set fits the
    VMEM budget (0 = none does)."""
    if k_facts % 32 != 0:
        return 0
    for b in (512, 256, 128, 64, 32):
        if n % b == 0 and fused_vmem_bytes(
                b, k_facts, stamp_cols, deferred) <= VMEM_BUDGET_BYTES:
            return b
    return 0


def fused_ok(n: int, k_facts: int, stamp_cols: int,
             deferred: bool = False) -> Tuple[bool, str]:
    """Can the fused kernel family run on an ``n``-row shard?  Returns
    ``(ok, reason)`` — the reason string is what the loud fallback
    (flight event + ``serf.pallas.fused_fallback`` counter) records, so
    an operator can tell a shape rejection from a VMEM rejection.  On
    the sharded path callers pass the PER-CHIP row count n/P.
    ``deferred`` configs gate on the flush kernel's larger working set
    (overlay term included) so a config that fits per-round but not
    deferred falls back loudly rather than OOMing at the first flush."""
    if k_facts % 32 != 0:
        return False, f"k_facts {k_facts} not a multiple of 32"
    if _block_for(n) == 0:
        return False, f"no supported node block divides n={n}"
    if _fused_block(n, k_facts, stamp_cols, deferred) == 0:
        smallest = fused_vmem_bytes(32, k_facts, stamp_cols, deferred)
        return False, (
            f"VMEM working set {smallest >> 20} MiB at the smallest "
            f"block exceeds the {VMEM_BUDGET_BYTES >> 20} MiB budget "
            f"(k_facts={k_facts})")
    return True, ""


def _maybe_shard(fn, mesh, n_arrays: int, n_scalars: int,
                 n_out: int = 1):
    """Wrap ``fn(*scalars, *arrays) -> out`` in shard_map over the node
    axis: scalar (1, 1) operands replicate, plane operands shard on axis
    0, all ``n_out`` outputs shard on axis 0 (the only pattern the
    kernel family produces — per-chip row blocks, flags included).
    ``fn`` must build its pallas_call from the (then per-chip) array
    shapes it receives.  ``mesh=None`` returns ``fn`` unchanged."""
    if mesh is None:
        return fn
    try:
        from jax import shard_map
    except ImportError:  # pre-0.5 jax exposes it under experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from serf_tpu.parallel.mesh import NODE_AXIS
    in_specs = (P(None, None),) * n_scalars + (P(NODE_AXIS, None),) * n_arrays
    spec = P(NODE_AXIS, None)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=(spec,) * n_out if n_out > 1 else spec,
                     check_rep=False)


def _unpack_words(words: jnp.ndarray, k: int) -> jnp.ndarray:
    """(B, W) u32 -> (B, K) bool via static repeat + per-lane shift (no
    gathers; pltpu.repeat tiles, so repeat a 1-wide slice per word)."""
    w = words.shape[1]
    groups = [pltpu.repeat(words[:, wi:wi + 1], 32, axis=1)
              for wi in range(w)]
    repeated = jnp.concatenate(groups, axis=1)                 # (B, K)
    shifts = (jax.lax.broadcasted_iota(jnp.uint32, (1, k), 1) % 32)
    return ((repeated >> shifts) & 1).astype(bool)


def _pack_bits(mask: jnp.ndarray, k: int) -> jnp.ndarray:
    """(B, K) bool -> (B, W) u32.  Mosaic has no unsigned reductions; sum
    in int32 and bitcast.  Each weight 1<<j appears at most once per word,
    so the signed sum is any 32-bit pattern reinterpreted — always
    representable, never overflows."""
    w = k // 32
    bits = mask.astype(jnp.int32)
    weights = (jnp.int32(1) << (
        jax.lax.broadcasted_iota(jnp.int32, (1, k), 1) % 32))
    weighted = bits * weights                      # (B, K)
    words = []
    for wi in range(w):
        words.append(jnp.sum(weighted[:, wi * 32:(wi + 1) * 32], axis=1,
                             keepdims=True, dtype=jnp.int32))
    return jax.lax.bitcast_convert_type(
        jnp.concatenate(words, axis=1), jnp.uint32)


def _weave_pair_words(ok_lo: jnp.ndarray, ok_hi: jnp.ndarray,
                      k: int) -> jnp.ndarray:
    """Per-nibble predicate bits (two (B, K/2) i32 0/1 arrays) -> (B, W)
    u32 fact words: weave fact ``2c+p`` of byte column ``c`` into bit
    ``2*(c%16)+p`` of word ``c//16`` with a weighted i32 sum (each weight
    used once per word — representable, never overflows).  The in-kernel
    twin of ``dissemination.pack_pred_words``."""
    c = ok_lo.shape[1]
    w = k // 32
    bytepos = (jax.lax.broadcasted_iota(jnp.int32, (1, c), 1) % 16)
    weighted = (ok_lo * (jnp.int32(1) << (2 * bytepos))
                + ok_hi * (jnp.int32(1) << (2 * bytepos + 1)))
    words = []
    for wi in range(w):
        words.append(jnp.sum(weighted[:, wi * 16:(wi + 1) * 16], axis=1,
                             keepdims=True, dtype=jnp.int32))
    return jax.lax.bitcast_convert_type(
        jnp.concatenate(words, axis=1), jnp.uint32)


def _nibble_pred_words(stamp_i32: jnp.ndarray, rq, limit_q,
                       k: int) -> jnp.ndarray:
    """(B, K/2) i32 packed-stamp bytes -> (B, W) u32 of per-fact
    ``q-age < limit_q`` bits, without ever widening to K lanes: evaluate
    both nibbles per byte column, then weave (:func:`_weave_pair_words`)."""
    lo = stamp_i32 & 0xF
    hi = (stamp_i32 >> 4) & 0xF
    ok_lo = (((rq - lo) & 0xF) < limit_q).astype(jnp.int32)
    ok_hi = (((rq - hi) & 0xF) < limit_q).astype(jnp.int32)
    return _weave_pair_words(ok_lo, ok_hi, k)


def _learn_pairs(new_words: jnp.ndarray, c: int) -> Tuple[jnp.ndarray,
                                                          jnp.ndarray]:
    """(B, W) u32 learn bits -> two (B, C) bools: did the byte column's
    low/high nibble fact just get learned (byte c holds facts 2c, 2c+1 =
    bits 2*(c%16), 2*(c%16)+1 of word c//16)."""
    w = new_words.shape[1]
    groups = [pltpu.repeat(new_words[:, wi:wi + 1], 16, axis=1)
              for wi in range(w)]
    repeated = jnp.concatenate(groups, axis=1)                 # (B, C)
    shifts = 2 * (jax.lax.broadcasted_iota(jnp.uint32, (1, c), 1) % 16)
    pair = (repeated >> shifts) & 3
    return (pair & 1) > 0, (pair & 2) > 0


def _clamped(nib: jnp.ndarray, rq, pin) -> jnp.ndarray:
    """Inline wrap clamp on i32 nibble values (clamp_nibbles, in-kernel)."""
    qage = (rq - nib) & 0xF
    return jnp.where(qage > pin, (rq - pin) & 0xF, nib)


# ---------------------------------------------------------------------------
# phase 1: packet selection
# ---------------------------------------------------------------------------


def _make_select_kernel(packed: bool, k: int):
    def kernel(limit_ref, round_ref, stamp_ref, known_ref, alive_ref,
               packets_ref):
        known = known_ref[:]                       # (B, W) u32
        alive = alive_ref[:]                       # (B, 1) u8
        limit_q = limit_ref[0, 0]                  # i32
        rq = round_ref[0, 0]                       # i32, already mod 16
        # derived q-age predicate (mod-16 wrap): valid only where the
        # known bit is set — the AND below gates it
        if packed:
            age_ok = _nibble_pred_words(stamp_ref[:].astype(jnp.int32),
                                        rq, limit_q, k)
        else:
            nib = stamp_ref[:].astype(jnp.int32)   # (B, K)
            ok = ((rq - nib) & 0xF) < limit_q
            age_ok = _pack_bits(ok, k)
        alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))
        packets_ref[:] = known & age_ok & alive_words

    return kernel


def select_packets(stamp: jnp.ndarray, known: jnp.ndarray,
                   alive_u8: jnp.ndarray, limit_q: int, round_, *,
                   packed: bool, k_facts: int,
                   mesh=None) -> jnp.ndarray:
    """packets u32[N,W]: one read-only pass over the stamp plane + known
    words (q-ages derive from stamps; nothing is ticked anywhere).

    With ``mesh`` the call runs under shard_map over the node axis (each
    chip streams its N/P block) — the fused family's stale-cache branch
    on the sharded flagship path."""
    n, c = stamp.shape
    k = k_facts
    w = k // 32
    from serf_tpu.models.dissemination import round_q

    limit_arr = jnp.asarray(limit_q, jnp.int32).reshape(1, 1)
    round_arr = round_q(round_).astype(jnp.int32).reshape(1, 1)

    def call(limit_arr, round_arr, stamp, known, alive_u8):
        nl = stamp.shape[0]                        # per-chip under mesh
        # prefer the VMEM-gated block so fused_ok's budget governs the
        # kernel actually dispatched (fused_ok guarantees it exists on
        # every fused-path call, sharded or not); only the standalone
        # path — gated by the VMEM-blind pallas_ok — may fall back to
        # the shape-only block, its documented PR-3 status quo
        block = _fused_block(nl, k, c) or _block_for(nl)
        grid = (nl // block,)
        return pl.pallas_call(
            _make_select_kernel(packed, k),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((block, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((block, w), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((nl, w), jnp.uint32),
            interpret=_interpret(),
        )(limit_arr, round_arr, stamp, known, alive_u8)

    # host wall clock only: eager calls time a real dispatch (first call
    # at a shape = compile), calls inside an outer jit time the trace
    with dispatch_timer("ops.select_packets", signature=(n, k, packed)):
        return _maybe_shard(call, mesh, n_arrays=3, n_scalars=2)(
            limit_arr, round_arr, stamp, known, alive_u8)


# ---------------------------------------------------------------------------
# phase 3: merge incoming
# ---------------------------------------------------------------------------


def _make_merge_kernel(packed: bool, k: int, pin: int):
    def kernel(round_ref, known_ref, incoming_ref, alive_ref, stamp_ref,
               known_out_ref, stamp_out_ref):
        known = known_ref[:]                       # (B, W) u32
        incoming = incoming_ref[:]                 # (B, W) u32
        alive = alive_ref[:]                       # (B, 1) u8
        rq = round_ref[0, 0]                       # i32, already mod 16
        alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))
        new_words = incoming & ~known & alive_words    # (B, W)
        known_out_ref[:] = known | new_words
        if packed:
            b = stamp_ref[:].astype(jnp.int32)     # (B, C)
            lo = _clamped(b & 0xF, rq, pin)
            hi = _clamped((b >> 4) & 0xF, rq, pin)
            lo_learn, hi_learn = _learn_pairs(new_words, b.shape[1])
            nlo = jnp.where(lo_learn, rq, lo)
            nhi = jnp.where(hi_learn, rq, hi)
            stamp_out_ref[:] = (nlo | (nhi << 4)).astype(jnp.uint8)
        else:
            nib = _clamped(stamp_ref[:].astype(jnp.int32), rq, pin)
            new_mask = _unpack_words(new_words, k)     # (B, K) bool
            stamp_out_ref[:] = jnp.where(new_mask, rq,
                                         nib).astype(jnp.uint8)

    return kernel


def merge_incoming(known: jnp.ndarray, incoming: jnp.ndarray,
                   alive_u8: jnp.ndarray, stamp: jnp.ndarray, next_round,
                   *, packed: bool, k_facts: int
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(known', stamp') in one fused pass: learn new facts, stamp them
    with ``next_round``'s quarter (the post-increment round — first
    visible at derived q-age 0 in the next round's selection), and re-pin
    wrap-stale stamps while the plane streams (clamp_nibbles inline —
    callers may bump ``last_clamp``)."""
    from serf_tpu.models.dissemination import AGE_PIN_Q, round_q

    n, c = stamp.shape
    k = k_facts
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    round_arr = round_q(next_round).astype(jnp.int32).reshape(1, 1)
    with dispatch_timer("ops.merge_incoming", signature=(n, k, packed)):
        return pl.pallas_call(
            _make_merge_kernel(packed, k, AGE_PIN_Q),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n, w), jnp.uint32),
                jax.ShapeDtypeStruct((n, c), jnp.uint8),
            ],
            interpret=_interpret(),
        )(round_arr, known, incoming, alive_u8, stamp)


# ---------------------------------------------------------------------------
# the fused-round family (cache-maintaining; shard_map-ready)
# ---------------------------------------------------------------------------


def _make_fused_select_kernel():
    def kernel(sendable_ref, known_ref, alive_ref, packets_ref):
        alive = alive_ref[:]                       # (B, 1) u8
        alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))
        # the AND with `known` masks stale cache bits for retired ring
        # slots (GossipState.sendable_round invariant) — identical to
        # the XLA cached select
        packets_ref[:] = sendable_ref[:] & known_ref[:] & alive_words

    return kernel


def fused_select_cached(sendable: jnp.ndarray, known: jnp.ndarray,
                        alive_u8: jnp.ndarray, *, k_facts: int,
                        stamp_cols: int, mesh=None) -> jnp.ndarray:
    """Selection off the VALID sendable cache: a word-plane-only kernel
    (no stamp read at all — the pass the fused family removes from the
    standalone-kernel round).  Callers must guard on
    ``sendable_round == round``; the stale branch is
    :func:`select_packets`."""
    n, w = known.shape

    def call(sendable, known, alive_u8):
        nl = known.shape[0]
        block = _fused_block(nl, k_facts, stamp_cols)
        return pl.pallas_call(
            _make_fused_select_kernel(),
            grid=(nl // block,),
            in_specs=[
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((block, w), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((nl, w), jnp.uint32),
            interpret=_interpret(),
        )(sendable, known, alive_u8)

    with dispatch_timer("ops.fused_select", signature=(n, k_facts)):
        return _maybe_shard(call, mesh, n_arrays=3, n_scalars=0)(
            sendable, known, alive_u8)


def _make_fused_merge_kernel(packed: bool, k: int, pin: int,
                             with_cache: bool):
    """Merge + stamp learn + inline clamp + (optionally) the sendable-
    cache recompute for round+1 — the in-kernel twin of
    ``dissemination.learn_stamp_pass``, sharing its exact arithmetic so
    the fused round is bit-exact with the XLA path by construction."""

    def kernel(round_ref, limit_ref, known_ref, incoming_ref, alive_ref,
               stamp_ref, *out_refs):
        if with_cache:
            known_out_ref, stamp_out_ref, send_out_ref, flag_ref = out_refs
        else:
            known_out_ref, stamp_out_ref, flag_ref = out_refs
        known = known_ref[:]                       # (B, W) u32
        incoming = incoming_ref[:]                 # (B, W) u32
        alive = alive_ref[:]                       # (B, 1) u8
        rq = round_ref[0, 0]                       # i32, already mod 16
        limit_q = limit_ref[0, 0]                  # i32
        alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))
        new_words = incoming & ~known & alive_words    # (B, W)
        known2 = known | new_words
        known_out_ref[:] = known2
        # per-block learn flag: callers OR the (grid, 1) column into the
        # round's `learned_any` — definitional (it IS the learn set), so
        # it can never desync from the kernel's learn semantics
        flag_ref[0, 0] = jnp.sum((new_words != 0).astype(jnp.int32))
        if packed:
            b = stamp_ref[:].astype(jnp.int32)     # (B, C)
            lo = _clamped(b & 0xF, rq, pin)
            hi = _clamped((b >> 4) & 0xF, rq, pin)
            lo_learn, hi_learn = _learn_pairs(new_words, b.shape[1])
            nlo = jnp.where(lo_learn, rq, lo)
            nhi = jnp.where(hi_learn, rq, hi)
            stamp_out_ref[:] = (nlo | (nhi << 4)).astype(jnp.uint8)
            if with_cache:
                # sendable' for round+1 from the just-written nibbles —
                # both already in registers, so the cache recompute costs
                # only the output write (learn_stamp_pass pays an extra
                # XLA pass for the same plane)
                ok_lo = (((rq - nlo) & 0xF) < limit_q).astype(jnp.int32)
                ok_hi = (((rq - nhi) & 0xF) < limit_q).astype(jnp.int32)
                send_out_ref[:] = known2 & _weave_pair_words(ok_lo, ok_hi,
                                                             k)
        else:
            nib = _clamped(stamp_ref[:].astype(jnp.int32), rq, pin)
            new_mask = _unpack_words(new_words, k)     # (B, K) bool
            nib2 = jnp.where(new_mask, rq, nib)
            stamp_out_ref[:] = nib2.astype(jnp.uint8)
            if with_cache:
                ok = (((rq - nib2) & 0xF) < limit_q)
                send_out_ref[:] = known2 & _pack_bits(ok, k)

    return kernel


def fused_merge(known: jnp.ndarray, incoming: jnp.ndarray,
                alive_u8: jnp.ndarray, stamp: jnp.ndarray, next_round,
                *, limit_q: int, packed: bool, k_facts: int,
                with_cache: bool, mesh=None):
    """The fused-round merge: ``(known', stamp', sendable'|None, flags)``
    in ONE streaming pass over every plane — learn new facts, stamp them
    with ``next_round``'s quarter, re-pin wrap-stale stamps, and (when
    ``with_cache``) recompute the sendable cache for ``next_round`` from
    the in-register nibbles.  ``flags`` is an i32[(grid), 1] per-block
    learn count; ``jnp.any(flags != 0)`` is the round's ``learned_any``.

    With ``mesh`` the whole call runs under shard_map over the node axis
    — the per-chip grid streams N/P rows, which is what keeps the
    8-chip sharded flagship on the pallas fast path."""
    from serf_tpu.models.dissemination import AGE_PIN_Q, round_q

    n, c = stamp.shape
    k = k_facts
    w = k // 32
    round_arr = round_q(next_round).astype(jnp.int32).reshape(1, 1)
    limit_arr = jnp.asarray(limit_q, jnp.int32).reshape(1, 1)

    def call(round_arr, limit_arr, known, incoming, alive_u8, stamp):
        nl = stamp.shape[0]
        block = _fused_block(nl, k, c)
        grid = (nl // block,)
        out_specs = [
            pl.BlockSpec((block, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, c), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((nl, w), jnp.uint32),
            jax.ShapeDtypeStruct((nl, c), jnp.uint8),
        ]
        if with_cache:
            out_specs.append(pl.BlockSpec((block, w), lambda i: (i, 0),
                                          memory_space=pltpu.VMEM))
            out_shape.append(jax.ShapeDtypeStruct((nl, w), jnp.uint32))
        out_specs.append(pl.BlockSpec((1, 1), lambda i: (i, 0),
                                      memory_space=pltpu.SMEM))
        out_shape.append(jax.ShapeDtypeStruct((nl // block, 1), jnp.int32))
        return pl.pallas_call(
            _make_fused_merge_kernel(packed, k, AGE_PIN_Q, with_cache),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=_interpret(),
        )(round_arr, limit_arr, known, incoming, alive_u8, stamp)

    with dispatch_timer("ops.fused_merge",
                        signature=(n, k, packed, with_cache)):
        out = _maybe_shard(call, mesh, n_arrays=4, n_scalars=2,
                           n_out=4 if with_cache else 3)(
            round_arr, limit_arr, known, incoming, alive_u8, stamp)
    if with_cache:
        return out[0], out[1], out[2], out[3]
    return out[0], out[1], None, out[2]


# ---------------------------------------------------------------------------
# deferred-stamp flush (quarter-deferred flavor, PR-18)
# ---------------------------------------------------------------------------


def _make_fused_flush_kernel(packed: bool, k: int, pin: int,
                             with_cache: bool):
    """The cohort flush in one streaming stamp pass — the in-kernel twin
    of ``dissemination.flush_stamp_pass``, sharing its exact arithmetic
    (clamp at the flush round, overlay cells to the cohort's quarter,
    fresh learns to the flush round's quarter — new wins over a stale
    overlay bit — then the cache recompute from the final nibbles).

    There is deliberately NO defer-round kernel: a mid-cohort merge is
    word-plane ORs only (known/overlay/sendable — no stamp touch), which
    XLA already fuses bandwidth-optimally; the stamp pass this kernel
    amortizes IS the pass the deferred flavor removes from those
    rounds."""

    def kernel(round_ref, prev_ref, limit_ref, known_ref, new_ref,
               overlay_ref, stamp_ref, *out_refs):
        if with_cache:
            stamp_out_ref, send_out_ref = out_refs
        else:
            stamp_out_ref, = out_refs
        known2 = known_ref[:]                      # (B, W) u32, POST-merge
        new_words = new_ref[:]                     # (B, W) u32 this merge
        overlay = overlay_ref[:]                   # (B, W) u32 cohort learns
        rq = round_ref[0, 0]                       # i32, already mod 16
        rq_prev = prev_ref[0, 0]                   # i32: the cohort quarter
        limit_q = limit_ref[0, 0]                  # i32
        if packed:
            b = stamp_ref[:].astype(jnp.int32)     # (B, C)
            lo = _clamped(b & 0xF, rq, pin)
            hi = _clamped((b >> 4) & 0xF, rq, pin)
            o_lo, o_hi = _learn_pairs(overlay, b.shape[1])
            lo = jnp.where(o_lo, rq_prev, lo)
            hi = jnp.where(o_hi, rq_prev, hi)
            n_lo, n_hi = _learn_pairs(new_words, b.shape[1])
            nlo = jnp.where(n_lo, rq, lo)
            nhi = jnp.where(n_hi, rq, hi)
            stamp_out_ref[:] = (nlo | (nhi << 4)).astype(jnp.uint8)
            if with_cache:
                ok_lo = (((rq - nlo) & 0xF) < limit_q).astype(jnp.int32)
                ok_hi = (((rq - nhi) & 0xF) < limit_q).astype(jnp.int32)
                send_out_ref[:] = known2 & _weave_pair_words(ok_lo, ok_hi,
                                                             k)
        else:
            nib = _clamped(stamp_ref[:].astype(jnp.int32), rq, pin)
            nib = jnp.where(_unpack_words(overlay, k), rq_prev, nib)
            nib2 = jnp.where(_unpack_words(new_words, k), rq, nib)
            stamp_out_ref[:] = nib2.astype(jnp.uint8)
            if with_cache:
                ok = ((rq - nib2) & 0xF) < limit_q
                send_out_ref[:] = known2 & _pack_bits(ok, k)

    return kernel


def fused_flush(known2: jnp.ndarray, new_words: jnp.ndarray,
                overlay: jnp.ndarray, stamp: jnp.ndarray, next_round,
                *, limit_q: int, packed: bool, k_facts: int,
                with_cache: bool, mesh=None):
    """The deferred flavor's once-per-cohort stamp flush:
    ``(stamp', sendable'|None)`` in ONE streaming pass over the stamp
    plane — re-pin wrap-stale stamps at ``next_round``, write every
    pending overlay cell with the cohort quarter
    ``round_q(next_round - 1)``, stamp this merge's fresh learns with
    ``round_q(next_round)``, and (when ``with_cache``) recompute the
    sendable cache for ``next_round`` from the in-register nibbles.
    ``known2`` is the POST-merge known plane; the caller owns the
    word-plane merge (mid-cohort rounds never call this — they are
    word-plane ORs with no stamp touch at all) and clears the overlay /
    bumps ``last_flush`` afterwards.

    With ``mesh`` the call runs under shard_map over the node axis, the
    same per-chip streaming contract as :func:`fused_merge`."""
    from serf_tpu.models.dissemination import AGE_PIN_Q, round_q

    n, c = stamp.shape
    k = k_facts
    w = k // 32
    round_arr = round_q(next_round).astype(jnp.int32).reshape(1, 1)
    prev_arr = round_q(
        jnp.asarray(next_round, jnp.int32) - 1).astype(jnp.int32).reshape(
            1, 1)
    limit_arr = jnp.asarray(limit_q, jnp.int32).reshape(1, 1)

    def call(round_arr, prev_arr, limit_arr, known2, new_words, overlay,
             stamp):
        nl = stamp.shape[0]
        block = _fused_block(nl, k, c, deferred=True)
        grid = (nl // block,)
        out_specs = [
            pl.BlockSpec((block, c), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((nl, c), jnp.uint8),
        ]
        if with_cache:
            out_specs.append(pl.BlockSpec((block, w), lambda i: (i, 0),
                                          memory_space=pltpu.VMEM))
            out_shape.append(jax.ShapeDtypeStruct((nl, w), jnp.uint32))
        return pl.pallas_call(
            _make_fused_flush_kernel(packed, k, AGE_PIN_Q, with_cache),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((block, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=out_specs if with_cache else out_specs[0],
            out_shape=out_shape if with_cache else out_shape[0],
            interpret=_interpret(),
        )(round_arr, prev_arr, limit_arr, known2, new_words, overlay,
          stamp)

    with dispatch_timer("ops.fused_flush",
                        signature=(n, k, packed, with_cache)):
        out = _maybe_shard(call, mesh, n_arrays=4, n_scalars=3,
                           n_out=2 if with_cache else 1)(
            round_arr, prev_arr, limit_arr, known2, new_words, overlay,
            stamp)
    if with_cache:
        return out[0], out[1]
    return out, None
