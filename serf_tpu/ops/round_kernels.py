"""Pallas TPU kernels for the gossip round's elementwise phases.

The round kernel (serf_tpu/models/dissemination.py) has three phases:

1. packet selection: pack ``known & (derived q-age < transmit_limit_q) &
   alive`` into uint32 words (a fact's age derives from its 4-bit
   learn-quarter stamp — see ``GossipState``; nothing ticks),
2. pull-exchange: peer read + OR-reduce (left to XLA — rolls/gathers are
   already bandwidth-optimal and fuse with the RNG),
3. merge: learn new facts (bit ops over N×W), stamp them with the
   post-increment round's quarter, and re-pin wrap-stale stamps
   (``clamp_nibbles`` folded in — a fresh stamp is a fresh budget, and
   the standalone clamp pass never needs to fire after a merge).

Phases 1 and 3 each touch the stamp plane (u8[N, K/2] nibble-packed by
default, u8[N, K] for the unpacked A/B flavor) plus the N×W word plane;
under plain XLA they materialize several N×K intermediates (the sending
mask, the unpacked known/new-fact masks).  These kernels fuse each phase
into a single pass: one read and one write per array, everything else in
VMEM registers.  The packed flavor never widens to K lanes at all: both
nibbles' age predicates are evaluated per BYTE column and woven straight
into u32 words (fact ``2c+p`` of byte ``c`` is bit ``2*(c%16)+p`` of
word ``c//16``), so selection is pure word-plane arithmetic.  The XLA
path in ``dissemination.py`` remains the semantic oracle; parity is
pinned by tests (interpret mode on CPU, compiled on TPU).

Layout notes (pallas_guide.md): blocks are (BLOCK_N, C) uint8 / (BLOCK_N,
W) uint32 in VMEM; scalars ride SMEM as (1, 1); iota is 2-D
broadcasted_iota; unpacking uses a static repeat + per-lane shift, no
gathers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from serf_tpu.obs.device import dispatch_timer


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _block_for(n: int) -> int:
    """Largest supported node-block size dividing N."""
    for b in (512, 256, 128, 64, 32):
        if n % b == 0:
            return b
    return 0


def pallas_ok(n: int, k_facts: int) -> bool:
    """Shapes the kernels support: a node block divides N, K is a multiple
    of 32 (the word size — which also keeps the nibble-packed plane at a
    whole number of 16-byte word groups).

    SINGLE-DEVICE ONLY: a ``pallas_call`` grid over the full N axis is
    not partitionable by GSPMD, so the sharded flagship round
    (``cluster_round(..., mesh=)``) disables the pallas path at trace
    time and records a ``pallas-fallback`` flight event
    (``parallel.ring.sharded_round_step``) — re-enabling it there means
    wrapping these kernels in shard_map over the node-block grid, which
    is exactly how they are written (per-block bodies), but is left for
    the fused-megakernel round (ROADMAP item 2)."""
    return _block_for(n) > 0 and k_facts % 32 == 0


def _unpack_words(words: jnp.ndarray, k: int) -> jnp.ndarray:
    """(B, W) u32 -> (B, K) bool via static repeat + per-lane shift (no
    gathers; pltpu.repeat tiles, so repeat a 1-wide slice per word)."""
    w = words.shape[1]
    groups = [pltpu.repeat(words[:, wi:wi + 1], 32, axis=1)
              for wi in range(w)]
    repeated = jnp.concatenate(groups, axis=1)                 # (B, K)
    shifts = (jax.lax.broadcasted_iota(jnp.uint32, (1, k), 1) % 32)
    return ((repeated >> shifts) & 1).astype(bool)


def _pack_bits(mask: jnp.ndarray, k: int) -> jnp.ndarray:
    """(B, K) bool -> (B, W) u32.  Mosaic has no unsigned reductions; sum
    in int32 and bitcast.  Each weight 1<<j appears at most once per word,
    so the signed sum is any 32-bit pattern reinterpreted — always
    representable, never overflows."""
    w = k // 32
    bits = mask.astype(jnp.int32)
    weights = (jnp.int32(1) << (
        jax.lax.broadcasted_iota(jnp.int32, (1, k), 1) % 32))
    weighted = bits * weights                      # (B, K)
    words = []
    for wi in range(w):
        words.append(jnp.sum(weighted[:, wi * 32:(wi + 1) * 32], axis=1,
                             keepdims=True, dtype=jnp.int32))
    return jax.lax.bitcast_convert_type(
        jnp.concatenate(words, axis=1), jnp.uint32)


def _nibble_pred_words(stamp_i32: jnp.ndarray, rq, limit_q,
                       k: int) -> jnp.ndarray:
    """(B, K/2) i32 packed-stamp bytes -> (B, W) u32 of per-fact
    ``q-age < limit_q`` bits, without ever widening to K lanes: evaluate
    both nibbles per byte column, then weave fact ``2c+p`` into bit
    ``2*(c%16)+p`` of word ``c//16`` with a weighted i32 sum (each weight
    used once per word — representable, never overflows)."""
    c = stamp_i32.shape[1]
    w = k // 32
    lo = stamp_i32 & 0xF
    hi = (stamp_i32 >> 4) & 0xF
    ok_lo = (((rq - lo) & 0xF) < limit_q).astype(jnp.int32)
    ok_hi = (((rq - hi) & 0xF) < limit_q).astype(jnp.int32)
    bytepos = (jax.lax.broadcasted_iota(jnp.int32, (1, c), 1) % 16)
    weighted = (ok_lo * (jnp.int32(1) << (2 * bytepos))
                + ok_hi * (jnp.int32(1) << (2 * bytepos + 1)))
    words = []
    for wi in range(w):
        words.append(jnp.sum(weighted[:, wi * 16:(wi + 1) * 16], axis=1,
                             keepdims=True, dtype=jnp.int32))
    return jax.lax.bitcast_convert_type(
        jnp.concatenate(words, axis=1), jnp.uint32)


def _learn_pairs(new_words: jnp.ndarray, c: int) -> Tuple[jnp.ndarray,
                                                          jnp.ndarray]:
    """(B, W) u32 learn bits -> two (B, C) bools: did the byte column's
    low/high nibble fact just get learned (byte c holds facts 2c, 2c+1 =
    bits 2*(c%16), 2*(c%16)+1 of word c//16)."""
    w = new_words.shape[1]
    groups = [pltpu.repeat(new_words[:, wi:wi + 1], 16, axis=1)
              for wi in range(w)]
    repeated = jnp.concatenate(groups, axis=1)                 # (B, C)
    shifts = 2 * (jax.lax.broadcasted_iota(jnp.uint32, (1, c), 1) % 16)
    pair = (repeated >> shifts) & 3
    return (pair & 1) > 0, (pair & 2) > 0


def _clamped(nib: jnp.ndarray, rq, pin) -> jnp.ndarray:
    """Inline wrap clamp on i32 nibble values (clamp_nibbles, in-kernel)."""
    qage = (rq - nib) & 0xF
    return jnp.where(qage > pin, (rq - pin) & 0xF, nib)


# ---------------------------------------------------------------------------
# phase 1: packet selection
# ---------------------------------------------------------------------------


def _make_select_kernel(packed: bool, k: int):
    def kernel(limit_ref, round_ref, stamp_ref, known_ref, alive_ref,
               packets_ref):
        known = known_ref[:]                       # (B, W) u32
        alive = alive_ref[:]                       # (B, 1) u8
        limit_q = limit_ref[0, 0]                  # i32
        rq = round_ref[0, 0]                       # i32, already mod 16
        # derived q-age predicate (mod-16 wrap): valid only where the
        # known bit is set — the AND below gates it
        if packed:
            age_ok = _nibble_pred_words(stamp_ref[:].astype(jnp.int32),
                                        rq, limit_q, k)
        else:
            nib = stamp_ref[:].astype(jnp.int32)   # (B, K)
            ok = ((rq - nib) & 0xF) < limit_q
            age_ok = _pack_bits(ok, k)
        alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))
        packets_ref[:] = known & age_ok & alive_words

    return kernel


def select_packets(stamp: jnp.ndarray, known: jnp.ndarray,
                   alive_u8: jnp.ndarray, limit_q: int, round_, *,
                   packed: bool, k_facts: int) -> jnp.ndarray:
    """packets u32[N,W]: one read-only pass over the stamp plane + known
    words (q-ages derive from stamps; nothing is ticked anywhere)."""
    n, c = stamp.shape
    k = k_facts
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    from serf_tpu.models.dissemination import round_q

    limit_arr = jnp.asarray(limit_q, jnp.int32).reshape(1, 1)
    round_arr = round_q(round_).astype(jnp.int32).reshape(1, 1)
    # host wall clock only: eager calls time a real dispatch (first call
    # at a shape = compile), calls inside an outer jit time the trace
    with dispatch_timer("ops.select_packets", signature=(n, k, packed)):
        return pl.pallas_call(
            _make_select_kernel(packed, k),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((BLOCK_N, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n, w), jnp.uint32),
            interpret=_interpret(),
        )(limit_arr, round_arr, stamp, known, alive_u8)


# ---------------------------------------------------------------------------
# phase 3: merge incoming
# ---------------------------------------------------------------------------


def _make_merge_kernel(packed: bool, k: int, pin: int):
    def kernel(round_ref, known_ref, incoming_ref, alive_ref, stamp_ref,
               known_out_ref, stamp_out_ref):
        known = known_ref[:]                       # (B, W) u32
        incoming = incoming_ref[:]                 # (B, W) u32
        alive = alive_ref[:]                       # (B, 1) u8
        rq = round_ref[0, 0]                       # i32, already mod 16
        alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))
        new_words = incoming & ~known & alive_words    # (B, W)
        known_out_ref[:] = known | new_words
        if packed:
            b = stamp_ref[:].astype(jnp.int32)     # (B, C)
            lo = _clamped(b & 0xF, rq, pin)
            hi = _clamped((b >> 4) & 0xF, rq, pin)
            lo_learn, hi_learn = _learn_pairs(new_words, b.shape[1])
            nlo = jnp.where(lo_learn, rq, lo)
            nhi = jnp.where(hi_learn, rq, hi)
            stamp_out_ref[:] = (nlo | (nhi << 4)).astype(jnp.uint8)
        else:
            nib = _clamped(stamp_ref[:].astype(jnp.int32), rq, pin)
            new_mask = _unpack_words(new_words, k)     # (B, K) bool
            stamp_out_ref[:] = jnp.where(new_mask, rq,
                                         nib).astype(jnp.uint8)

    return kernel


def merge_incoming(known: jnp.ndarray, incoming: jnp.ndarray,
                   alive_u8: jnp.ndarray, stamp: jnp.ndarray, next_round,
                   *, packed: bool, k_facts: int
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(known', stamp') in one fused pass: learn new facts, stamp them
    with ``next_round``'s quarter (the post-increment round — first
    visible at derived q-age 0 in the next round's selection), and re-pin
    wrap-stale stamps while the plane streams (clamp_nibbles inline —
    callers may bump ``last_clamp``)."""
    from serf_tpu.models.dissemination import AGE_PIN_Q, round_q

    n, c = stamp.shape
    k = k_facts
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    round_arr = round_q(next_round).astype(jnp.int32).reshape(1, 1)
    with dispatch_timer("ops.merge_incoming", signature=(n, k, packed)):
        return pl.pallas_call(
            _make_merge_kernel(packed, k, AGE_PIN_Q),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BLOCK_N, c), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n, w), jnp.uint32),
                jax.ShapeDtypeStruct((n, c), jnp.uint8),
            ],
            interpret=_interpret(),
        )(round_arr, known, incoming, alive_u8, stamp)
