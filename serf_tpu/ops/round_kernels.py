"""Pallas TPU kernels for the gossip round's elementwise phases.

The round kernel (serf_tpu/models/dissemination.py) has three phases:

1. packet selection: pack ``age < transmit_limit & alive`` into uint32
   words (a fact's remaining transmit budget is derived from its knowledge
   age — see ``GossipState``) and tick the saturating age,
2. pull-exchange: peer read + OR-reduce (left to XLA — rolls/gathers are
   already bandwidth-optimal and fuse with the RNG),
3. merge: learn new facts (bit ops over N×W) and reset knowledge ages
   (N×K) — age 0 is a fresh budget.

Phases 1 and 3 each touch the N×K uint8 age plane plus the N×W word
plane; under plain XLA they materialize several N×K intermediates (the
sending mask, the unpacked new-fact mask).  These kernels fuse each phase
into a single pass: one read and one write per array, everything else in
VMEM registers.  The XLA path in ``dissemination.py`` remains the semantic
oracle; parity is pinned by tests (interpret mode on CPU, compiled on TPU).

Layout notes (pallas_guide.md): blocks are (BLOCK_N, K) uint8 / (BLOCK_N, W)
uint32 in VMEM; scalars ride SMEM as (1, 1); iota is 2-D broadcasted_iota;
unpacking uses a static repeat + per-lane shift, no gathers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _block_for(n: int) -> int:
    """Largest supported node-block size dividing N."""
    for b in (512, 256, 128, 64, 32):
        if n % b == 0:
            return b
    return 0


def pallas_ok(n: int, k_facts: int) -> bool:
    """Shapes the kernels support: a node block divides N, K is a multiple
    of 32 (the word size)."""
    return _block_for(n) > 0 and k_facts % 32 == 0


# ---------------------------------------------------------------------------
# phase 1: packet selection
# ---------------------------------------------------------------------------


def _select_kernel(limit_ref, age_ref, alive_ref, packets_ref):
    age = age_ref[:]                               # (B, K) u8
    alive = alive_ref[:]                           # (B, 1) u8
    k = age.shape[1]
    w = k // 32
    limit = limit_ref[0, 0].astype(jnp.uint8)
    sending = (age < limit) & (alive > 0)          # (B, K) bool
    # Mosaic has no unsigned reductions; sum in int32 and bitcast.  Each
    # weight 1<<j appears at most once per word, so the signed sum is any
    # 32-bit pattern reinterpreted — always representable, never overflows.
    bits = sending.astype(jnp.int32)
    weights = (jnp.int32(1) << (
        jax.lax.broadcasted_iota(jnp.int32, (1, k), 1) % 32))
    weighted = bits * weights                      # (B, K)
    # sum each 32-lane group into one word
    words = []
    for wi in range(w):
        words.append(jnp.sum(weighted[:, wi * 32:(wi + 1) * 32], axis=1,
                             keepdims=True, dtype=jnp.int32))
    packets_ref[:] = jax.lax.bitcast_convert_type(
        jnp.concatenate(words, axis=1), jnp.uint32)


def select_packets(age: jnp.ndarray, alive_u8: jnp.ndarray, limit: int
                   ) -> jnp.ndarray:
    """packets u32[N,W]: one read-only pass over the age plane (the
    saturating age++ lives in the merge kernel's single write)."""
    n, k = age.shape
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    limit_arr = jnp.asarray(limit, jnp.int32).reshape(1, 1)
    return pl.pallas_call(
        _select_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, w), jnp.uint32),
        interpret=_interpret(),
    )(limit_arr, age, alive_u8)


# ---------------------------------------------------------------------------
# phase 3: merge incoming
# ---------------------------------------------------------------------------


def _merge_kernel(known_ref, incoming_ref, alive_ref, age_ref,
                  known_out_ref, age_out_ref):
    known = known_ref[:]                           # (B, W) u32
    incoming = incoming_ref[:]                     # (B, W) u32
    alive = alive_ref[:]                           # (B, 1) u8
    age = age_ref[:]                               # (B, K) u8
    k = age.shape[1]
    alive_words = jnp.where(alive > 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    new_words = incoming & ~known & alive_words    # (B, W)
    known_out_ref[:] = known | new_words
    # unpack: column k must read word k//32 — broadcast each single word
    # column to 32 lanes (pltpu.repeat tiles, so repeat a 1-wide slice),
    # concat the groups, then shift by k%32
    w = new_words.shape[1]
    groups = [pltpu.repeat(new_words[:, wi:wi + 1], 32, axis=1)
              for wi in range(w)]
    repeated = jnp.concatenate(groups, axis=1)                 # (B, K)
    shifts = (jax.lax.broadcasted_iota(jnp.uint32, (1, k), 1) % 32)
    new_mask = ((repeated >> shifts) & 1).astype(bool)
    aged = jnp.where(age < 255, age + 1, age)      # saturating age++
    age_out_ref[:] = jnp.where(new_mask, jnp.uint8(0), aged)


def merge_incoming(known: jnp.ndarray, incoming: jnp.ndarray,
                   alive_u8: jnp.ndarray, age: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(known', age') in one fused pass: learn + saturating age++ + age-0
    reset for newly learned facts (age 0 = fresh transmit budget).  Takes
    the PRE-increment age (selection's view)."""
    n, k = age.shape
    w = k // 32
    BLOCK_N = _block_for(n)
    grid = (n // BLOCK_N,)
    return pl.pallas_call(
        _merge_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, w), jnp.uint32),
            jax.ShapeDtypeStruct((n, k), jnp.uint8),
        ],
        interpret=_interpret(),
    )(known, incoming, alive_u8, age)
