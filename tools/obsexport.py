#!/usr/bin/env python
"""Export the unified cross-plane observability timeline.

    # run a named FaultPlan and ship its six-surface timeline bundle
    python tools/obsexport.py --plan query-storm --plane host -o run.trace.json
    python tools/obsexport.py --plan partition-heal-loss --plane both \
        -o chaos.trace.json

    # validate an existing bundle (exit 0 iff schema-clean)
    python tools/obsexport.py --validate run.trace.json

The bundle is Chrome-trace-event JSON: open it at https://ui.perfetto.dev
(or chrome://tracing) — one process lane per node plus a device-plane
process, per-surface thread lanes (spans, flight, lifecycle stages,
control, SLO).  ``tools/chaos.py --export-timeline`` and ``bench.py
--export-timeline`` write the same bundle beside their own reports; this
tool is the standalone driver + validator.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _export_plan(plan_name: str, plane: str, out: str, n: int,
                 k_facts: int) -> int:
    from serf_tpu.faults.plan import named_plan, plan_names
    from serf_tpu.obs import slo
    from serf_tpu.obs.timeline import (
        DeviceRunAnchors,
        PiecewiseAnchors,
        TimelineBuilder,
        export_run_timeline,
        validate_timeline,
    )

    try:
        plan = named_plan(plan_name)
    except KeyError:
        print(f"unknown plan {plan_name!r}; available: "
              f"{', '.join(plan_names())}", file=sys.stderr)
        return 2

    host_result = host_verdicts = None
    device_result = device_anchors = device_verdicts = None
    if plane in ("host", "both"):
        from serf_tpu.faults.host import run_host_plan
        with tempfile.TemporaryDirectory(prefix="serf-obsexport-") as td:
            host_result = asyncio.run(run_host_plan(plan, tmp_dir=td))
        host_verdicts = slo.judge_host_run(host_result, plan)
    if plane in ("device", "both"):
        from serf_tpu.faults.device import run_device_plan
        from serf_tpu.models.swim import flagship_config
        cfg = flagship_config(n, k_facts=k_facts)
        t0 = time.time()
        device_result = run_device_plan(plan, cfg, collect_telemetry=True)
        device_anchors = (
            PiecewiseAnchors(device_result.scan_walls)
            if device_result.scan_walls else DeviceRunAnchors(
                wall_start=t0, wall_end=time.time(),
                rounds=device_result.rounds_run))
        device_verdicts = slo.judge_device_run(device_result, plan)

    path = export_run_timeline(
        out, host_result=host_result, host_verdicts=host_verdicts,
        device_result=device_result, device_anchors=device_anchors,
        device_verdicts=device_verdicts,
        meta={"plan": plan.name, "plane": plane},
        builder=TimelineBuilder(meta={"plan": plan.name, "plane": plane}))
    with open(path) as f:
        doc = json.load(f)
    problems = validate_timeline(doc)
    n_events = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"wrote {path}: {n_events} events, surfaces "
          f"{doc['otherData']['surfaces']} "
          f"({'valid' if not problems else 'INVALID: ' + problems[0]})")
    print("open at https://ui.perfetto.dev (Open trace file)")
    return 0 if not problems else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plan", default="query-storm",
                    help="named FaultPlan to run and export")
    ap.add_argument("--plane", choices=("host", "device", "both"),
                    default="host")
    ap.add_argument("-o", "--out", default="serf.trace.json",
                    help="output bundle path")
    ap.add_argument("--n", type=int, default=256,
                    help="device-plane simulated node count")
    ap.add_argument("--k-facts", type=int, default=32)
    ap.add_argument("--validate", metavar="PATH",
                    help="validate an existing bundle instead of running")
    args = ap.parse_args()

    if args.validate:
        from serf_tpu.obs.timeline import validate_timeline
        with open(args.validate) as f:
            doc = json.load(f)
        problems = validate_timeline(doc)
        for p in problems:
            print(p, file=sys.stderr)
        print(f"{args.validate}: "
              f"{'valid' if not problems else f'{len(problems)} problem(s)'}")
        return 0 if not problems else 1
    return _export_plan(args.plan, args.plane, args.out, args.n,
                        args.k_facts)


if __name__ == "__main__":
    sys.exit(main())
