"""Micro-benchmark the irregular ops at 1M: random gather vs rolled slice,
scatter-max, top_k.  Establishes the per-op cost table driving the
rotation-sampling redesign."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

print("platform:", jax.devices()[0].device_kind, flush=True)

n = 1_000_000


def timed(tag, fn, *args, reps=10):
    try:
        out = fn(*args)
        jax.block_until_ready(out)
        # host sync on a scalar derived from the output
        leaf = jax.tree.leaves(out)[0]
        float(jnp.sum(leaf.astype(jnp.float32)[:1]))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        leaf = jax.tree.leaves(out)[0]
        float(jnp.sum(leaf.astype(jnp.float32)[:1]))
        ms = 1000 * (time.perf_counter() - t0) / reps
        print(f"{tag}: {ms:.2f} ms", flush=True)
    except Exception as e:
        print(f"{tag} ERROR: {repr(e)[:300]}", flush=True)


key = jax.random.key(0)
packets = jax.random.randint(key, (n, 2), 0, 2**31 - 1).astype(jnp.uint32)
vec8 = jax.random.uniform(key, (n, 8), jnp.float32)
srcs = jax.random.randint(key, (n, 3), 0, n)
peer = jax.random.randint(key, (n,), 0, n)
bools = jax.random.bernoulli(key, 0.5, (n,))
score = jax.random.uniform(key, (n,), jnp.float32)
targets = jax.random.randint(key, (n,), 0, n)


@jax.jit
def gather_rows_w2(p, s):
    return p[s]                     # u32[N,3,2] random gather


@jax.jit
def gather_rows_f8(v, s):
    return v[s]                     # f32[N,8] random gather


@jax.jit
def gather_bool(b, s):
    return b[s]


@jax.jit
def rolled(x, shift):
    return jax.lax.dynamic_slice_in_dim(
        jnp.concatenate([x, x], axis=0), shift, n, axis=0)


@jax.jit
def roll3(p, offs):
    acc = jnp.zeros_like(p)
    for f in range(3):
        acc = acc | rolled(p, offs[f])
    return acc


@jax.jit
def scatter_max(b, t):
    return jnp.zeros((n,), bool).at[t].max(b)


@jax.jit
def scatter_max_i32(t, w):
    return jnp.zeros((n,), jnp.int32).at[t].max(w)


@jax.jit
def topk8(s):
    return jax.lax.top_k(s, 8)


@jax.jit
def unpack_refute_like(known):
    bits = (known[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    m = bits.reshape(n, 64).astype(bool)
    return jnp.any(m, axis=1)


offs = jax.random.randint(key, (3,), 1, n)
timed("gather_1M_rows_u32x2_fanout3", gather_rows_w2, packets, srcs)
timed("gather_1M_rows_f32x8", gather_rows_f8, vec8, peer)
timed("gather_1M_bool", gather_bool, bools, peer)
timed("rolled_u32x2", rolled, packets, offs[0])
timed("rolled_f32x8", rolled, vec8, offs[0])
timed("roll3_or_u32x2", roll3, packets, offs)
timed("scatter_max_1M_bool", scatter_max, bools, targets)
timed("scatter_max_1M_i32", scatter_max_i32, targets,
      jnp.arange(n, dtype=jnp.int32))
timed("top_k8_1M", topk8, score)
timed("unpack64_any_1M", unpack_refute_like, packets)

print("microbench complete", flush=True)
