#!/usr/bin/env python
"""One-shot TPU evidence session: everything we need from ONE device claim.

The axon tunnel allows one client at a time and wedges if a client dies
mid-claim, so when the TPU is reachable we must capture all hardware
evidence in a single, foreground, never-killed process:

1. **Compiled Pallas parity** — run the fused round kernels with
   ``interpret=False`` on the real chip and assert bit-equality against the
   XLA path (round-1 verdict: interpret-mode-only Pallas is unverified).
2. **Sustained headline** — `run_cluster_sustained` @1M, 2 events/round
   (bench.py's metric of record since round 5).
2a. **Flagship steady + active** — `cluster_round` @1M, both regimes.
3. **swim-only bench** + **Pallas A/B** @1M.

Rehearsal: ``SERF_TPU_PROOF_REHEARSAL=1 python tools/tpu_proof.py`` runs
every stage on CPU at n=20k writing to /tmp — validates the script's
plumbing between tunnel-healthy sessions without faking evidence.

Writes ``TPU_PROOF.json`` at the repo root and prints a summary.  A
Pallas compile/parity failure does NOT abort the session (the bench
stages are the headline evidence) but is recorded per-stage, flips the
top-level ``ok`` to false, and the script exits 1.  Run in the
foreground: ``python tools/tpu_proof.py``.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "TPU_PROOF.json")


def main() -> int:
    import jax
    import jax.numpy as jnp

    proof = {"stages": {}}

    def record(stage, **kv):
        proof["stages"][stage] = kv
        with open(OUT, "w") as f:
            json.dump(proof, f, indent=1)
        print(f"[{stage}] {kv}", flush=True)

    # Rehearsal mode (SERF_TPU_PROOF_REHEARSAL=1): exercise every stage's
    # PLUMBING on CPU at small n, writing to /tmp — never TPU_PROOF.json.
    # Exists because round 3 proved the failure mode of a proof script
    # that only runs when the tunnel is healthy: it breaks silently in
    # between (the r4 script crashed at stage 2 against the r4
    # _time_rounds signature and nothing caught it).
    rehearsal = os.environ.get("SERF_TPU_PROOF_REHEARSAL") == "1"
    global OUT
    if rehearsal:
        OUT = "/tmp/tpu_proof_rehearsal.json"
        proof["rehearsal"] = True
        # force the CPU platform via config update BEFORE any backend
        # touch: the axon site hook registers the real-TPU plugin at
        # interpreter start and env JAX_PLATFORMS=cpu alone loses to it —
        # without this the rehearsal claims (and can hang on) the tunnel,
        # the exact thing a rehearsal exists to avoid
        jax.config.update("jax_platforms", "cpu")

    devs = jax.devices()
    proof["platform"] = f"{len(devs)}x {devs[0].device_kind}"
    proof["backend"] = jax.default_backend()
    if jax.default_backend() == "cpu" and not rehearsal:
        print("ERROR: no TPU backend — refusing to fake TPU evidence",
              flush=True)
        record("platform_check", ok=False, backend="cpu")
        return 1
    record("platform_check", ok=True, platform=proof["platform"],
           rehearsal=rehearsal)

    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_USER_EVENT,
        coverage,
        inject_fact,
        make_state,
        round_step,
    )
    from serf_tpu.models.failure import run_swim
    from serf_tpu.models.swim import (
        flagship_config,
        make_cluster,
        run_cluster,
        run_cluster_sustained,
    )
    from serf_tpu.ops import round_kernels

    # -- stage 1: compiled Pallas parity (modest n: compile fast, assert
    #    bit-equality over several rounds).  A Mosaic compile failure (the
    #    kernels use sub-128 lane dims, legal-but-risky layouts) must NOT
    #    abort the session — the bench stages are the headline evidence.
    pallas_failed = False
    try:
        n_par = 8192
        cfg_x = GossipConfig(n=n_par, k_facts=64, use_pallas=False)
        cfg_p = GossipConfig(n=n_par, k_facts=64, use_pallas=True)
        st = inject_fact(make_state(cfg_x), cfg_x, 3, K_USER_EVENT, 0, 1, 0)
        step_x = jax.jit(functools.partial(round_step, cfg=cfg_x))
        step_p = jax.jit(functools.partial(round_step, cfg=cfg_p))
        a = b = st
        key = jax.random.key(0)
        t0 = time.perf_counter()
        equal = True
        for _ in range(20):
            key, k2 = jax.random.split(key)
            a = step_x(a, key=k2)
            b = step_p(b, key=k2)
        jax.block_until_ready((a, b))
        for name in ("known", "stamp"):
            if not bool(jnp.all(getattr(a, name) == getattr(b, name))):
                equal = False
                record("pallas_parity", ok=False, mismatch=name)
        if equal:
            # record the kernels' ACTUAL mode: on the forced-CPU
            # rehearsal backend _interpret() is True — claiming compiled
            # evidence there would be fabrication
            record("pallas_parity", ok=True, n=n_par, rounds=20,
                   interpret=bool(round_kernels._interpret()),
                   seconds=round(time.perf_counter() - t0, 1))
        else:
            pallas_failed = True
    except Exception as e:  # noqa: BLE001 - keep capturing evidence
        pallas_failed = True
        err = repr(e)[:500]
        # the axon tunnel's remote compile helper crashing (HTTP 500) is an
        # environment failure, not a kernel bug: interpret-mode parity is
        # green and a trivial kernel compiles through the same helper
        env_blocked = "remote_compile" in err and "HTTP 500" in err
        record("pallas_parity", ok=False, env_blocked=env_blocked, error=err)

    # -- timing helper: bench.py's host-transfer barrier (one shared
    # implementation — see _time_rounds there for why block_until_ready
    # is NOT a trustworthy completion barrier on this tunnel).  Takes a
    # state FACTORY (the r4 signature: warmup runs on the first seeded
    # state; measure_active re-seeds to time the detection-hot window).
    from bench import _time_rounds

    def timed(jitted, state_factory, rounds_per_call=100, calls=3,
              measure_active=False):
        return _time_rounds(jitted, state_factory, jax.random.key(1),
                            rounds_per_call, calls,
                            measure_active=measure_active)

    n = 20_000 if rehearsal else 1_000_000
    # THE flagship workload (swim.flagship_config — same definition as
    # bench.py and the accounting budget): rotation sampling, round-robin
    # probes, reference LAN gossip:probe cadence, push/pull every 16
    ccfg = flagship_config(n)
    gcfg, fcfg = ccfg.gossip, ccfg.failure

    def seeded():
        st = make_cluster(ccfg, jax.random.key(0))
        g = st.gossip
        spacing = n // 8
        for i in range(8):
            g = inject_fact(g, gcfg, subject=i * spacing, kind=K_USER_EVENT,
                            incarnation=0, ltime=i + 1, origin=i * spacing)
        # dead ids offset by 1 so no fact origin dies (a dead origin
        # can't gossip its fact — coverage would sit at 0 by design)
        dead = jnp.arange(64) * (n // 64) + 1
        g = g._replace(alive=g.alive.at[dead].set(False))
        return st._replace(gossip=g)

    # -- stage 2: SUSTAINED headline (bench.py's metric of record:
    #    2 fresh user events injected per round keep the quiescent gate
    #    open — the number that rewards doing the work faster) ----------
    run_sus = jax.jit(functools.partial(run_cluster_sustained, cfg=ccfg,
                                        events_per_round=2),
                      static_argnames=("num_rounds",), donate_argnums=(0,))
    sus_st, sus_rps, _ = timed(run_sus, seeded)
    g_s = sus_st.gossip
    gate_gap = int(g_s.round) - int(g_s.last_learn)
    mean_cov = float(jnp.where(g_s.facts.valid, coverage(g_s, gcfg),
                               0.0).mean())
    record("sustained_1m", rps=round(sus_rps, 1),
           vs_10k_target=round(sus_rps / 10_000.0, 3),
           gate_gap=gate_gap, mean_coverage=round(mean_cov, 3))

    # -- stage 2a: flagship steady state + detection-hot active window ----
    run_flag = jax.jit(functools.partial(run_cluster, cfg=ccfg),
                       static_argnames=("num_rounds",), donate_argnums=(0,))
    st, rps, active_rps = timed(run_flag, seeded, measure_active=True)
    cov = float(coverage(st.gossip, gcfg)[0])
    record("flagship_1m", rps=round(rps, 1),
           active_rps=round(active_rps, 1), coverage0=cov)

    # -- stage 2b: flagship with the fused Pallas select/merge kernels
    #    (the VERDICT-r3 #4 lever: fusion in the HEADLINE path, not just
    #    the swim subset) — best-effort like every Pallas stage
    if not pallas_failed:
        try:
            ccfg_p = dataclasses.replace(
                ccfg, gossip=dataclasses.replace(gcfg, use_pallas=True))
            run_fp = jax.jit(functools.partial(run_cluster, cfg=ccfg_p),
                             static_argnames=("num_rounds",),
                             donate_argnums=(0,))
            _, fp_rps, _ = timed(run_fp, seeded)
            record("flagship_1m_pallas", rps=round(fp_rps, 1),
                   speedup_vs_xla=round(fp_rps / rps, 3))
        except Exception as e:  # noqa: BLE001 - keep capturing evidence
            pallas_failed = True
            record("flagship_1m_pallas", ok=False, error=repr(e)[:500])

    # -- stage 3: swim-only + Pallas A/B ------------------------------------
    run_sw = jax.jit(functools.partial(run_swim, cfg=gcfg, fcfg=fcfg),
                     static_argnames=("num_rounds",), donate_argnums=(0,))
    _, sw_rps, _ = timed(run_sw, lambda: seeded().gossip)
    record("swim_1m", rps=round(sw_rps, 1))

    if not pallas_failed:
        try:
            gcfg_p = dataclasses.replace(gcfg, use_pallas=True)
            run_pl = jax.jit(
                functools.partial(run_swim, cfg=gcfg_p, fcfg=fcfg),
                static_argnames=("num_rounds",), donate_argnums=(0,))
            _, pl_rps, _ = timed(run_pl, lambda: seeded().gossip)
            record("swim_1m_pallas", rps=round(pl_rps, 1),
                   speedup_vs_xla=round(pl_rps / sw_rps, 3))
        except Exception as e:  # noqa: BLE001 - keep capturing evidence
            pallas_failed = True
            record("swim_1m_pallas", ok=False, error=repr(e)[:500])
    else:
        record("swim_1m_pallas", skipped=True,
               reason="pallas_parity stage failed")

    # iid sampling + random probes A/B: the random-gather/scatter mode the
    # rotation redesign replaced (each 1M-row gather/scatter is a serial
    # loop on TPU)
    gcfg_iid = dataclasses.replace(gcfg, peer_sampling="iid")
    fcfg_iid = dataclasses.replace(fcfg, probe_schedule="random")
    run_iid = jax.jit(functools.partial(run_swim, cfg=gcfg_iid,
                                        fcfg=fcfg_iid),
                      static_argnames=("num_rounds",), donate_argnums=(0,))
    _, iid_rps, _ = timed(run_iid, lambda: seeded().gossip)
    record("swim_1m_iid", rps=round(iid_rps, 1),
           rotation_speedup=round(sw_rps / max(iid_rps, 1e-9), 3))

    proof["ok"] = not pallas_failed
    with open(OUT, "w") as f:
        json.dump(proof, f, indent=1)
    print("TPU proof complete:", json.dumps(proof["stages"]), flush=True)
    return 0 if proof["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
