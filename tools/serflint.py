#!/usr/bin/env python
"""serflint CLI — the repo's static-analysis gate (serf_tpu.analysis).

    python tools/serflint.py                  # lint the repo, exit 0/1
    python tools/serflint.py --json           # machine-readable report
    python tools/serflint.py --rule async-fire-forget [paths...]
    python tools/serflint.py --fix-baseline   # grandfather current findings
    python tools/serflint.py --bump-schema    # deliberate schema-pin bump

Exit codes: 0 = no new findings; 1 = new findings (printed); 2 = usage.

The gate is *zero new findings*: suppressed findings (``# serflint:
ignore[rule] -- reason``) and baselined findings (serflint_baseline.json,
reason-annotated) don't fail it, but a suppression/baseline entry without
a reason, or one matching nothing, does.  Wired into tier-1 via
tests/test_serflint.py (like ``chaos.py --self-check``).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from serf_tpu import analysis                      # noqa: E402
from serf_tpu.analysis import schema               # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="restrict file-scope rules to these files")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule id (repeatable)")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite serflint_baseline.json to cover every "
                         "current finding (new entries get a TODO reason "
                         "the gate refuses until annotated)")
    ap.add_argument("--bump-schema", action="store_true",
                    help="recompute the pytree/wire schema fingerprints "
                         "and bump the version of whichever changed")
    args = ap.parse_args(argv)

    project = analysis.default_project()

    if args.bump_schema:
        pins = schema.bump_pins(project.root, project.pins_path)
        print(json.dumps(pins, indent=1) if args.as_json else
              f"serflint: schema pins now {pins}")
        return 0

    restricted = bool(args.paths)
    files = analysis.collect_files(
        project, only=args.paths or None)

    if args.fix_baseline:
        # always over the FULL scan set: a path-restricted rewrite would
        # drop every entry for an out-of-view file
        if restricted:
            print("serflint: --fix-baseline ignores positional paths "
                  "(the baseline covers the whole tree)", file=sys.stderr)
        n = analysis.fix_baseline(project)
        print(f"serflint: baseline rewritten with {n} entries — annotate "
              "every TODO reason before the gate passes")
        return 0

    if args.rule:
        unknown = [r for r in args.rule if r not in analysis.ALL_RULES]
        if unknown:
            print(f"serflint: unknown rule(s) {unknown}; known: "
                  f"{sorted(analysis.ALL_RULES)}", file=sys.stderr)
            return 2
        if restricted:
            # project-scope rules judge the WHOLE tree; silently skipping
            # an explicitly requested one would green-light a broken gate
            skipped = [r for r in args.rule
                       if analysis.ALL_RULES[r].scope != "file"]
            if skipped:
                print(f"serflint: rule(s) {skipped} are project-scope and "
                      "need the full tree — drop the positional paths to "
                      "run them", file=sys.stderr)
                return 2

    report = analysis.run_rules(project, files=files, rules=args.rule,
                                file_scope_only=restricted)

    if args.as_json:
        pins = schema.load_pins(project.pins_path) \
            if project.pins_path and project.pins_path.exists() else {}
        print(json.dumps({
            "findings": [vars(f) for f in report.findings],
            "baselined": len(report.baselined),
            "suppressed": len(report.suppressed),
            "stale_baseline": report.stale_baseline,
            "rules": sorted(analysis.ALL_RULES),
            "schema_pins": pins,
        }, indent=1))
    else:
        for f in report.findings:
            print(f"{f.location()}: [{f.rule}] {f.message}")
        print(f"serflint: {len(report.findings)} new finding(s), "
              f"{len(report.baselined)} baselined, "
              f"{len(report.suppressed)} suppressed "
              f"({len(analysis.ALL_RULES)} rules)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
