#!/usr/bin/env python3
"""serfd: the serf agent entry point (one cluster member per process).

Thin wrapper over ``serf_tpu.host.agent`` — the importable module the
proc-plane chaos executor re-execs (``python -m serf_tpu.host.agent``).
Operators run this one:

    python tools/serfd.py --config agent.json

The config file is an ``AgentConfig`` JSON document::

    {
      "node_id": "p0",
      "bind": "127.0.0.1:0",
      "ctl": "127.0.0.1:0",
      "join": ["127.0.0.1:7946"],
      "snapshot_path": "/var/lib/serf/p0.snap",
      "ready_file": "/run/serf/p0.ready",
      "profile": "lan",
      "options": {"memberlist": {"probe_interval": "1s"}}
    }

``bind``/``ctl`` port 0 means ephemeral; once live the agent atomically
writes the ready file with the bound addresses, pid and restart
generation.  SIGTERM leaves gracefully (peers see Left, the snapshot
flushes the leave record); the control channel speaks the length-framed
JSON protocol in ``serf_tpu.host.ctl``.

Deliberately jax-free: agents are host-plane processes and must start
in fractions of a second.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from serf_tpu.host.agent import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
