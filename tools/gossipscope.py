#!/usr/bin/env python
"""gossipscope: trace how facts actually spread — the propagation
observatory's CLI (serf_tpu/obs/propagation.py, ISSUE 16).

Device mode (default) runs a named FaultPlan with the sentinel tracer
on — the first injected event batch is tagged and followed per round
inside the jitted scan — and renders:

- the **coverage curve** (ASCII, rounds on x, coverage on y, the
  50/90/99% SLO marks as gridlines) with time-to-X% and per-sentinel
  first-learn rounds;
- the **redundancy table**: the measured slots-sent / slots-learned
  ledger vs the analytic `1/(window·fanout)` model, and the resulting
  useful-vs-redundant byte split of the round floor
  (``models/accounting.propagation_split``) at the traced N and at the
  1M flagship.

Host mode (``--host``) stands up the loopback self-check cluster and
fires a traced probe: one user event whose TraceContext id is polled
across every node's PropagationLedger for coverage and
time-to-all-nodes.

    python tools/gossipscope.py                     # device trace
    python tools/gossipscope.py --plan crash-restart --n 128
    python tools/gossipscope.py --host              # loopback probe
    python tools/gossipscope.py --json              # machine-readable
    python tools/gossipscope.py --self-check        # tier-1 hook

``--self-check`` runs the tiny device trace and exits 0 iff the traced
run is sane: full sentinel coverage, a finite time-to-99%, and a
redundancy ratio inside (0, 1).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the trace scenario must run on CPU even where a TPU plugin is registered
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FLAGSHIP_N = 1_000_000


def run_device_trace(plan_name: str, n: int, k_facts: int) -> dict:
    """Run the plan with the sentinel tracer on; returns the summary
    dict + the byte-split tables (everything the render needs)."""
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.models.accounting import propagation_split
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig, flagship_config

    cfg = ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=k_facts,
                            peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8)
    plan = named_plan(plan_name)
    result = run_device_plan(plan, cfg, collect_telemetry=True,
                             collect_propagation=True)
    summary = result.propagation["summary"]
    return {
        "plan": plan.name,
        "report_ok": result.report.ok,
        "summary": summary,
        "split": propagation_split(
            cfg, measured_redundancy=summary["redundancy"]),
        "split_flagship": propagation_split(flagship_config(FLAGSHIP_N)),
    }


def run_host_probe() -> dict:
    """Loopback cluster + traced probe; returns the propagation dict
    (coverage, time-to-all, fold of every node's ledger)."""
    from serf_tpu.faults.host import run_host_plan
    from serf_tpu.faults.plan import named_plan

    plan = named_plan("self-check")
    with tempfile.TemporaryDirectory(prefix="serf-gossipscope-") as td:
        result = asyncio.run(run_host_plan(plan, tmp_dir=td))
    return {"plan": plan.name, "report_ok": result.report.ok,
            "propagation": result.propagation}


def _mb(b: float) -> str:
    if b >= 1e6:
        return f"{b / 1e6:8.1f} MB"
    return f"{b / 1e3:8.1f} KB"


def print_device(out: dict) -> None:
    from serf_tpu.obs.propagation import (
        COVERAGE_MARKS,
        format_propagation,
        render_coverage,
    )

    s = out["summary"]
    print(f"gossipscope: plan {out['plan']!r}, {s['sentinels']} "
          f"sentinel(s) traced over {s['rounds']} round(s)")
    print()
    print(render_coverage(s["curve"]))
    print()
    tt = s["time_to"]
    marks = "  ".join(f"t{m}%={_r(tt.get(str(m)))}" for m in COVERAGE_MARKS)
    print(f"coverage: {marks}   first-learn "
          f"{[_r(v) for v in s['first_learn']]}   "
          f"final {s['final_coverage']:.3f}")
    print(format_propagation(s, "device"))
    print()
    for label, sp in (("traced run", out["split"]),
                      (f"1M flagship (analytic)", out["split_flagship"])):
        print(f"redundancy — {label} (n={sp['n']:,}, "
              f"{sp['redundancy_source']} redundancy "
              f"{sp['redundancy']:.4f}, analytic "
              f"{sp['analytic_redundancy']:.4f}):")
        print(f"  round floor      {_mb(sp['total_bytes'])}")
        print(f"  dissemination    {_mb(sp['dissemination_bytes'])}"
              f"   (selection+exchange+merge)")
        print(f"    useful         {_mb(sp['useful_bytes'])}"
              f"   (taught a receiver a new fact)")
        print(f"    redundant      {_mb(sp['redundant_bytes'])}"
              f"   (epidemic re-teaching)")
        print(f"  other planes     {_mb(sp['other_bytes'])}")


def _r(v):
    return f"{v}r" if v is not None else "never"


def print_host(out: dict) -> None:
    from serf_tpu.obs.propagation import format_propagation

    p = out["propagation"]
    print(f"gossipscope: plan {out['plan']!r} (host loopback)")
    print(format_propagation(p, "host"))
    if p and p.get("trace"):
        print(f"  probe trace id {p['trace']} — ledger fold: "
              f"{p['seen']} seen, {p['duplicates']} duplicate(s), "
              f"{p['rebroadcasts']} rebroadcast(s)")


def self_check(out: dict) -> int:
    """Exit status for --self-check: the traced run must be sane."""
    s = out["summary"]
    problems = []
    if not out["report_ok"]:
        problems.append("invariant report not ok")
    if s["final_coverage"] < 1.0:
        problems.append(f"final coverage {s['final_coverage']:.3f} < 1")
    if s["time_to"].get("99") is None:
        problems.append("sentinels never reached 99% coverage")
    if not (0.0 < s["redundancy"] < 1.0):
        problems.append(f"redundancy {s['redundancy']:.3f} outside (0,1)")
    if problems:
        print("gossipscope: FAIL — " + "; ".join(problems),
              file=sys.stderr)
        return 1
    print("gossipscope: self-check ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--plan", default="partition-heal-loss",
                    help="device-plane FaultPlan to trace under "
                         "(default partition-heal-loss)")
    ap.add_argument("--n", type=int, default=64,
                    help="simulated node count (default 64)")
    ap.add_argument("--k-facts", type=int, default=32)
    ap.add_argument("--host", action="store_true",
                    help="host loopback probe instead of the device "
                         "sentinel trace")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-check", action="store_true",
                    help="tier-1 hook: tiny device trace, exit 0 iff "
                         "sane (full coverage, finite t99, redundancy "
                         "in (0,1))")
    args = ap.parse_args(argv)

    if args.self_check:
        args.host = False
        args.plan, args.n, args.k_facts = "self-check", 64, 32

    if args.host:
        out = run_host_probe()
        if args.json:
            print(json.dumps(out, indent=1, sort_keys=True))
        else:
            print_host(out)
        p = out["propagation"] or {}
        return 0 if out["report_ok"] and p.get("coverage") == 1.0 else 1

    out = run_device_trace(args.plan, args.n, args.k_facts)
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0 if out["report_ok"] else 1
    if args.self_check:
        return self_check(out)
    print_device(out)
    return 0 if out["report_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
