#!/usr/bin/env python
"""Render, diff and export black-box forensic bundles.

    # human-readable view of one bundle (what was the node doing?)
    python tools/blackbox.py render chaos-out/blackbox/blackbox-n3-000002.json

    # list every retained bundle under a directory, oldest first
    python tools/blackbox.py ls chaos-out/blackbox

    # what changed between two dumps of the same node?
    python tools/blackbox.py diff first.json second.json --json

    # feed the bundle into the unified Perfetto timeline
    python tools/blackbox.py timeline bundle.json -o breach.trace.json

    # tier-1 hook: synthetic breach -> dump -> validate/render/diff/
    # timeline round-trip, exit 0 iff clean
    python tools/blackbox.py self-check

Bundles are written by ``obs/blackbox.BlackBox`` when the always-on
watchdog (``obs/watchdog``) trips an invariant or a sustained SLO burn —
see README "Continuous verification & black box" for the pinned format
(``analysis/schema.py blackbox`` pin) and the breach workflow.  Every
subcommand validates before it touches content: a schema-drifted bundle
fails closed with the full problem list.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# render
# ---------------------------------------------------------------------------


def render_bundle(bundle: Dict[str, Any]) -> str:
    """One bundle as the breach-workflow summary: what tripped, when,
    what the node's recent history looked like."""
    meta = bundle["meta"]
    wd = bundle["watchdog"].get("state") or {}
    fl = bundle["flight"]
    lines = [
        f"black box  node={meta['node']}  seq={meta['seq']}  "
        f"schema=v{meta['version']}",
        f"  reason:    {meta['reason']}"
        + (f" ({meta['detail']})" if meta.get("detail") else ""),
        f"  wall:      {meta['wall_time']:.3f}",
    ]
    if wd:
        first = wd.get("first_breach")
        lines.append(
            f"  watchdog:  ticks={wd.get('ticks', 0)} "
            f"breaches={wd.get('breaches', 0)} "
            f"armed={len(wd.get('armed') or ())}"
            + (f" first-breach=tick {first.get('tick')} "
               f"[{','.join(first.get('breaches') or ())}]" if first else ""))
    events = fl.get("events") or []
    kinds: Dict[str, int] = {}
    for ev in events:
        kinds[ev.get("kind", "?")] = kinds.get(ev.get("kind", "?"), 0) + 1
    lines.append(
        f"  flight:    {len(events)} event(s) since seq "
        f"{fl.get('since_seq')} (dropped={fl.get('dropped', 0)})"
        + ("  " + " ".join(f"{k}x{n}" for k, n in sorted(kinds.items()))
           if kinds else ""))
    tails = bundle["series"].get("tails") or {}
    lines.append(f"  series:    {len(tails)} timeseries tail(s)")
    health = bundle["health"].get("report")
    if isinstance(health, dict) and "score" in health:
        lines.append(f"  health:    score={health['score']:.1f}")
    verdicts = bundle["slo"].get("verdicts") or []
    bad = [v for v in verdicts if not v.get("ok", True)]
    lines.append(f"  verdicts:  {len(verdicts)} retained, "
                 f"{len(bad)} breaching")
    recording = bundle["recording"].get("active")
    lines.append(f"  recording: "
                 + (json.dumps(recording, sort_keys=True)
                    if recording else "none"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------


def diff_bundles(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Structural delta between two bundles (typically consecutive dumps
    of one node): what moved between the forensic snapshots."""
    out: Dict[str, Any] = {"same": False}
    ma, mb = a["meta"], b["meta"]
    out["meta"] = {k: [ma.get(k), mb.get(k)]
                   for k in ("node", "seq", "reason", "wall_time")
                   if ma.get(k) != mb.get(k)}
    wa = a["watchdog"].get("state") or {}
    wb = b["watchdog"].get("state") or {}
    out["watchdog"] = {
        "ticks": [wa.get("ticks", 0), wb.get("ticks", 0)],
        "breaches": [wa.get("breaches", 0), wb.get("breaches", 0)],
    }
    seqs_a = {ev.get("seq") for ev in a["flight"].get("events") or []}
    seqs_b = {ev.get("seq") for ev in b["flight"].get("events") or []}
    out["flight"] = {"only_a": len(seqs_a - seqs_b),
                     "only_b": len(seqs_b - seqs_a)}
    keys_a = set(a["series"].get("tails") or {})
    keys_b = set(b["series"].get("tails") or {})
    out["series"] = {"only_a": sorted(keys_a - keys_b),
                     "only_b": sorted(keys_b - keys_a)}
    ha = (a["health"].get("report") or {}).get("score")
    hb = (b["health"].get("report") or {}).get("score")
    out["health"] = {"score": [ha, hb]}
    out["same"] = (not out["meta"]
                   and out["watchdog"]["ticks"][0]
                   == out["watchdog"]["ticks"][1]
                   and out["watchdog"]["breaches"][0]
                   == out["watchdog"]["breaches"][1]
                   and not out["flight"]["only_a"]
                   and not out["flight"]["only_b"])
    return out


def format_diff(d: Dict[str, Any]) -> str:
    if d["same"]:
        return "bundles identical (meta/watchdog/flight)"
    lines = ["bundles differ:"]
    for k, (va, vb) in sorted(d["meta"].items()):
        lines.append(f"  meta.{k}: {va!r} -> {vb!r}")
    ta, tb = d["watchdog"]["ticks"]
    ba, bb = d["watchdog"]["breaches"]
    if (ta, ba) != (tb, bb):
        lines.append(f"  watchdog: ticks {ta} -> {tb}, "
                     f"breaches {ba} -> {bb}")
    fa, fb = d["flight"]["only_a"], d["flight"]["only_b"]
    if fa or fb:
        lines.append(f"  flight: {fa} event(s) only in A, {fb} only in B")
    if d["series"]["only_a"] or d["series"]["only_b"]:
        lines.append(f"  series: -{len(d['series']['only_a'])} "
                     f"+{len(d['series']['only_b'])} key(s)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# timeline
# ---------------------------------------------------------------------------


def bundle_to_timeline(bundle: Dict[str, Any], out_path: str) -> str:
    """Export one bundle onto the unified Perfetto timeline: the flight
    tail on its routed lanes plus the watchdog verdict lane."""
    from serf_tpu.obs.timeline import (TimelineBuilder, validate_timeline,
                                       write_timeline)
    meta = bundle["meta"]
    b = TimelineBuilder(meta={"source": "blackbox", "node": meta["node"],
                              "reason": meta["reason"],
                              "seq": meta["seq"]})
    b.add_flight(bundle["flight"].get("events") or [])
    state = bundle["watchdog"].get("state") or {}
    if state:
        b.add_watchdog(state, float(meta["wall_time"]))
    doc = b.build()
    problems = validate_timeline(doc)
    if problems:
        raise ValueError("timeline export invalid: " + "; ".join(problems))
    return write_timeline(doc, out_path)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


def cmd_render(args) -> int:
    from serf_tpu.obs.blackbox import load_bundle
    bundle = load_bundle(args.bundle)
    if args.json:
        print(json.dumps(bundle, indent=1, sort_keys=True))
    else:
        print(render_bundle(bundle))
    return 0


def cmd_ls(args) -> int:
    from serf_tpu.obs.blackbox import validate_bundle
    try:
        names = sorted(n for n in os.listdir(args.directory)
                       if n.startswith("blackbox-") and n.endswith(".json"))
    except OSError as e:
        print(f"cannot list {args.directory}: {e}", file=sys.stderr)
        return 2
    rows = []
    for n in names:
        path = os.path.join(args.directory, n)
        try:
            with open(path, encoding="utf-8") as f:
                bundle = json.load(f)
            ok = not validate_bundle(bundle)
            meta = bundle.get("meta", {})
        except (OSError, json.JSONDecodeError):
            ok, meta = False, {}
        rows.append({"path": path, "valid": ok,
                     "node": meta.get("node"), "seq": meta.get("seq"),
                     "reason": meta.get("reason"),
                     "wall_time": meta.get("wall_time")})
    if args.json:
        print(json.dumps(rows, indent=1, sort_keys=True))
    else:
        for r in rows:
            print(f"{r['path']}  node={r['node']} seq={r['seq']} "
                  f"reason={r['reason']} "
                  f"{'' if r['valid'] else '[INVALID]'}".rstrip())
        print(f"{len(rows)} bundle(s)")
    return 0


def cmd_diff(args) -> int:
    from serf_tpu.obs.blackbox import load_bundle
    d = diff_bundles(load_bundle(args.a), load_bundle(args.b))
    if args.json:
        print(json.dumps(d, indent=1, sort_keys=True))
    else:
        print(format_diff(d))
    return 0 if d["same"] else 1


def cmd_timeline(args) -> int:
    from serf_tpu.obs.blackbox import load_bundle
    path = bundle_to_timeline(load_bundle(args.bundle), args.out)
    print(f"wrote {path} (open at https://ui.perfetto.dev)")
    return 0


def cmd_self_check(args) -> int:
    """Synthetic breach end-to-end: arm a watchdog, flip its invariant,
    verify the dumped bundle validates, renders, diffs and exports."""
    from serf_tpu.obs.blackbox import BlackBox, load_bundle
    from serf_tpu.obs.flight import FlightRecorder
    from serf_tpu.obs.watchdog import Watchdog, WatchdogConfig

    problems: List[str] = []
    with tempfile.TemporaryDirectory(prefix="serf-blackbox-") as td:
        rec = FlightRecorder()
        wd = Watchdog(cfg=WatchdogConfig(dump_every_ticks=1), recorder=rec)
        flag = {"ok": True}
        wd.arm("selfcheck-invariant",
               lambda: (flag["ok"], "synthetic predicate"))
        box = BlackBox(
            td, node="self", recorder=rec,
            health=lambda: {"score": 88.0, "components": {}},
            recording=lambda: {"plane": "host", "steps": 3,
                               "finished": True})
        wd.add_blackbox(box)
        rec.record("probe-failed", node="self", peer="n1")
        v1 = wd.tick(now=1.0)
        if not v1.ok:
            problems.append("green tick reported a breach")
        flag["ok"] = False
        v2 = wd.tick(now=2.0)
        if v2.ok or "selfcheck-invariant" not in v2.breaches:
            problems.append("breach tick missed the flipped invariant")
        paths = box.bundle_paths()
        if len(paths) != 1:
            problems.append(f"expected 1 bundle, found {len(paths)}")
        bundles = []
        for p in paths:
            try:
                bundles.append(load_bundle(p))
            except ValueError as e:
                problems.append(str(e))
        if bundles:
            text = render_bundle(bundles[0])
            if "selfcheck-invariant" not in json.dumps(
                    bundles[0]["watchdog"]):
                problems.append("bundle lost the breaching invariant name")
            if "black box" not in text:
                problems.append("render produced no header")
            # second dump -> the diff must notice the new bundle
            wd.tick(now=3.0)
            paths = box.bundle_paths()
            if len(paths) == 2:
                d = diff_bundles(bundles[0], load_bundle(paths[1]))
                if d["same"]:
                    problems.append("diff missed a seq/ticks change")
            else:
                problems.append("debounced second dump never landed")
            out = os.path.join(td, "bb.trace.json")
            try:
                bundle_to_timeline(bundles[0], out)
            except ValueError as e:
                problems.append(str(e))
    payload = {"ok": not problems, "problems": problems}
    if args.json:
        print(json.dumps(payload, indent=1, sort_keys=True))
    else:
        print("blackbox self-check: "
              + ("ok" if not problems else "; ".join(problems)))
    return 0 if not problems else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    rd = sub.add_parser("render", help="summarize one bundle")
    rd.add_argument("bundle")
    rd.add_argument("--json", action="store_true",
                    help="emit the validated bundle itself")
    rd.set_defaults(fn=cmd_render)

    ls = sub.add_parser("ls", help="list bundles under a directory")
    ls.add_argument("directory")
    ls.add_argument("--json", action="store_true")
    ls.set_defaults(fn=cmd_ls)

    df = sub.add_parser("diff", help="structural delta between bundles")
    df.add_argument("a")
    df.add_argument("b")
    df.add_argument("--json", action="store_true")
    df.set_defaults(fn=cmd_diff)

    tl = sub.add_parser("timeline", help="export a bundle as a Perfetto "
                                         "trace")
    tl.add_argument("bundle")
    tl.add_argument("-o", "--out", default="blackbox.trace.json")
    tl.set_defaults(fn=cmd_timeline)

    sc = sub.add_parser("self-check", help="synthetic breach round-trip "
                                           "(tier-1 hook)")
    sc.add_argument("--json", action="store_true")
    sc.set_defaults(fn=cmd_self_check)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
