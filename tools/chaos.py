#!/usr/bin/env python
"""Run a named FaultPlan on any plane and print the invariant report.

    python tools/chaos.py --plan partition-heal-loss --plane both
    python tools/chaos.py --plan crash-restart --plane host --json
    python tools/chaos.py --plan crash-restart --plane proc
    python tools/chaos.py --self-check          # tier-1 hook

The host plane stands up an in-process loopback cluster (snapshots in a
temp dir, so crash/restart plans exercise replay); the device plane runs
the flagship ``cluster_round`` with the plan lowered to per-round masks;
the proc plane spawns one OS process per node (``serf_tpu.host.agent``
on real sockets) and lowers crashes to SIGKILL, pauses to SIGSTOP, and
restarts to re-exec from the same snapshot directory.  Exit 0 iff every
invariant on every requested plane is green.  The degradation counter
block is the ``serf.faults.*`` / ``serf.degraded.*`` totals accumulated
during the run — the measured half of "graceful".
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_host(plan, recorder=None, controlled: bool = False):
    from serf_tpu.control.profiles import host_ab_profile
    from serf_tpu.faults.host import run_host_plan

    opts, ccfg = host_ab_profile(plan.name, controlled)
    with tempfile.TemporaryDirectory(prefix="serf-chaos-") as td:
        return asyncio.run(run_host_plan(plan, tmp_dir=td, opts=opts,
                                         recorder=recorder,
                                         controller=controlled,
                                         control_cfg=ccfg))


def run_device(plan, n: int, k_facts: int, devices: int = 0,
               recorder=None, collect_telemetry: bool = True,
               controlled: bool = False):
    from serf_tpu.control.profiles import device_ab_config
    from serf_tpu.faults.device import run_device_plan

    cfg = device_ab_config(plan.name, n, k_facts, controlled)
    # sharded flagship path: 0 = auto (largest visible device count that
    # divides n — a single-device host simply runs unsharded), 1 = force
    # unsharded, >1 = exactly that many devices (fail loud rather than
    # silently truncating — the report must never claim a mesh size
    # that did not run)
    mesh = None
    d = devices
    if d != 1:
        import jax

        from serf_tpu.parallel.mesh import best_device_count, make_mesh
        visible = len(jax.devices())
        if d == 0:
            d = best_device_count(n, visible)
        elif d > visible:
            raise SystemExit(
                f"--devices {d} exceeds the {visible} visible device(s) "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{d} for a virtual CPU mesh)")
        elif n % d != 0:
            raise SystemExit(
                f"--devices {d} does not divide --n {n}; pick a dividing "
                f"count (auto would use {best_device_count(n, visible)})")
        if d > 1:
            mesh = make_mesh(d)
    return (run_device_plan(plan, cfg, mesh=mesh, recorder=recorder,
                            collect_telemetry=collect_telemetry,
                            collect_propagation=True,
                            collect_invariants=True),
            (d if mesh else 1))


def run_proc(plan, record_dir: str = ".", record_on_fail: bool = False):
    """Proc plane: real processes on real sockets.  On a red run with
    ``record_on_fail``, EVERY process dumps its black-box bundle over
    the control channel and the bundles are copied out of the temp
    cluster dir before it is torn down."""
    import shutil

    from serf_tpu.faults.proc import run_proc_plan

    bundles = {}
    with tempfile.TemporaryDirectory(prefix="serf-chaos-proc-") as td:
        result = asyncio.run(run_proc_plan(
            plan, tmp_dir=td, blackbox_on_fail=record_on_fail))
        if record_on_fail and not result.report.ok:
            dest_root = os.path.join(record_dir,
                                     f"chaos-{plan.name}-proc.blackbox")
            for node_id, bdir in sorted(result.blackbox_dirs.items()):
                try:
                    if bdir and os.path.isdir(bdir) and os.listdir(bdir):
                        dst = os.path.join(dest_root, node_id)
                        shutil.copytree(bdir, dst, dirs_exist_ok=True)
                        bundles[node_id] = dst
                except OSError as e:
                    print(f"record-on-fail: could not copy {node_id} "
                          f"black box: {e}", file=sys.stderr)
            rot = getattr(result, "rotation", None)
            if rot is not None:
                # per-process keyring digests beside the copied bundles:
                # a red encrypted run must show which ring each process
                # died holding (never raw key material — digests only)
                try:
                    os.makedirs(dest_root, exist_ok=True)
                    with open(os.path.join(dest_root, "keyrings.json"),
                              "w", encoding="utf-8") as f:
                        json.dump(_rotation_forensics(rot), f, indent=1,
                                  sort_keys=True)
                        f.write("\n")
                    bundles["keyrings"] = os.path.join(dest_root,
                                                       "keyrings.json")
                except OSError as e:
                    print(f"record-on-fail: could not write keyring "
                          f"digests: {e}", file=sys.stderr)
    return result, bundles


def _rotation_forensics(rot):
    """The JSON-safe keyring-state slice of a rotation evidence dict:
    per-node ring digests, the expected post-rotation primary, and the
    convergence verdict (digests only — raw keys never leave a node)."""
    return {k: rot.get(k) for k in
            ("keyrings", "expected_primary", "converged", "latency_s",
             "reconcile_rounds") if k in rot}


def _dump_red_bundle(record_dir: str, plan, plane: str, result) -> str:
    """A red run's forensic half: one black-box bundle beside the replay
    artifact, fed from the process flight ring + the run's live watchdog
    verdict (host ``Watchdog.state()`` / device invariant summary)."""
    from serf_tpu.obs import flight
    from serf_tpu.obs.blackbox import BlackBox

    wd = getattr(result, "watchdog", None)
    if isinstance(wd, dict) and "rows" in wd:
        wd = {k: v for k, v in wd.items() if k != "rows"}  # host-side array
    rot = getattr(result, "rotation", None)
    if rot is not None:
        # keyring state digests ride the bundle's free-form watchdog
        # state (the schema pins sections, not state keys): a red
        # encrypted run is undiagnosable without "who held which ring"
        wd = dict(wd or {})
        wd["rotation"] = _rotation_forensics(rot)
    box = BlackBox(record_dir, node=f"{plan.name}-{plane}",
                   recorder=flight.global_recorder())
    return box.dump(reason="invariant-red",
                    detail=f"plan {plan.name} [{plane}]", watchdog=wd)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plan", default="partition-heal-loss")
    ap.add_argument("--plane", choices=("host", "device", "proc", "both"),
                    default="both",
                    help="'proc' spawns one real OS process per node "
                         "(serf_tpu.host.agent over real sockets); "
                         "'both' = host + device")
    ap.add_argument("--n", type=int, default=256,
                    help="device-plane simulated node count")
    ap.add_argument("--k-facts", type=int, default=32)
    ap.add_argument("--devices", type=int, default=0,
                    help="device-plane mesh size for the sharded "
                         "flagship round (0 = auto: largest visible "
                         "device count dividing --n; 1 = unsharded)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-check", action="store_true",
                    help="run the tiny self-check plan on both planes")
    ap.add_argument("--record-on-fail", dest="record_on_fail",
                    action="store_true", default=None,
                    help="attach the record/replay recorder and, on any "
                         "invariant failure, write the run's recording "
                         "plus a black-box bundle beside it as repro "
                         "artifacts (default: on for --self-check)")
    ap.add_argument("--no-record-on-fail", dest="record_on_fail",
                    action="store_false")
    ap.add_argument("--record-dir", default=".",
                    help="directory the failure recording is written to")
    ap.add_argument("--export-timeline", metavar="PATH", default=None,
                    help="write the unified cross-plane trace-event "
                         "timeline bundle (obs/timeline.py: spans, "
                         "flight, lifecycle, device rounds, control "
                         "decisions, SLO verdicts on one wall-clock "
                         "axis) to PATH — open it at "
                         "https://ui.perfetto.dev")
    ap.add_argument("--controller", choices=("off", "on", "ab"),
                    default="off",
                    help="adaptive control plane (serf_tpu.control): "
                         "'on' runs the plan with the controller "
                         "actuating the knobs; 'ab' runs each plane "
                         "twice — static vs controlled — and prints the "
                         "SLO verdicts side by side (config profiles: "
                         "serf_tpu/control/profiles.py)")
    args = ap.parse_args()

    from serf_tpu.faults.host import degradation_counters
    from serf_tpu.faults.plan import named_plan, plan_names

    if args.self_check:
        plan_name, planes = "self-check", ("host", "device")
        # the self-check is a tier-1 hook: keep the device side small
        # (compile time dominates; one phase-scan compile at modest n)
        # and UNSHARDED unless asked — the sharded chaos path has its
        # own tier-1 pin (tests/test_sharded_round.py) and the auto
        # mesh would grow this hook's compile on the 8-device harness
        args.n = min(args.n, 96)
        if args.devices == 0:
            args.devices = 1
    else:
        plan_name = args.plan
        planes = ("host", "device") if args.plane == "both" \
            else (args.plane,)
    try:
        plan = named_plan(plan_name)
    except KeyError:
        print(f"unknown plan {plan_name!r}; available: "
              f"{', '.join(plan_names())}", file=sys.stderr)
        return 2

    record_on_fail = args.record_on_fail
    if record_on_fail is None:
        record_on_fail = args.self_check

    def make_recorder():
        if not record_on_fail:
            return None
        from serf_tpu.replay.recording import RunRecorder
        return RunRecorder()

    from serf_tpu.obs import slo

    reports = []
    notes = []
    overload = {}
    recordings = {}
    blackboxes = {}
    watchdog_info = {}
    slo_verdicts = {}
    ring_summaries = {}
    control_info = {}
    lifecycle_info = {}
    propagation_info = {}
    rotation_info = {}
    ab = {}
    device_mesh = 1
    #: A/B mode runs each plane twice (static leg first); 'on' replaces
    #: the single run with the controlled one
    legs = {"off": (False,), "on": (True,), "ab": (False, True)}[
        args.controller]

    #: final-leg results + device wall anchors for --export-timeline
    import time as _time
    final_results = {}
    final_verdicts = {}
    device_anchor = {}

    def run_leg(plane, controlled, recorder):
        nonlocal device_mesh
        if plane == "host":
            result = run_host(plan, recorder=recorder,
                              controlled=controlled)
            verdicts = slo.judge_host_run(result, plan)
        else:
            t0 = _time.time()
            result, device_mesh = run_device(plan, args.n, args.k_facts,
                                             args.devices,
                                             recorder=recorder,
                                             controlled=controlled)
            device_anchor[plane] = (t0, _time.time())
            verdicts = slo.judge_device_run(result, plan)
        final_results[plane] = result
        final_verdicts[plane] = verdicts
        return result, verdicts

    proc_info = {}
    for plane in planes:
        if plane == "proc":
            # real processes, one run: no controller legs, no SLO
            # judging (host-plane SLOs assume in-process series access)
            result, proc_bundles = run_proc(plan, args.record_dir,
                                            record_on_fail)
            reports.append(result.report)
            if result.load is not None:
                overload["proc"] = result.load.to_dict()
            degraded = {k: v for k, v in sorted(
                result.survivor_counters.items())
                if k.startswith("serf.degraded.")
                or k == "memberlist.probe.failed"}
            proc_info = {
                "survivor_degradation": degraded,
                "settle_convergence_s": result.settle_convergence_s,
                "quiet_convergence_s": result.quiet_convergence_s,
                "processes": len(result.views),
                "spawned_pids": len(result.all_pids),
            }
            rot = getattr(result, "rotation", None)
            if rot is not None:
                # rotation-latency is the ONE host SLO the proc plane can
                # judge without in-process series access: the finale hands
                # back the measured reconvergence latency directly
                import math as _math
                rot_val = (float(rot.get("latency_s", _math.inf))
                           if rot.get("converged") else _math.inf)
                probes = rot.get("probes", {})
                slo_verdicts["proc"] = [slo.judge(
                    slo.slo_def("rotation-latency"), "proc", rot_val,
                    detail=f"{len(rot.get('keyrings', {}))} ring(s), "
                           f"{rot.get('reconcile_rounds', 0)} reconcile "
                           f"round(s)")]
                rotation_info["proc"] = rot
            lifecycle_info.update(
                {f"proc:{nid}": lc
                 for nid, lc in sorted(result.lifecycle.items())}
                if args.json else {})
            for node_id, path in sorted(proc_bundles.items()):
                blackboxes[f"proc:{node_id}"] = path
            continue
        for controlled in legs:
            is_final = controlled == legs[-1]
            recorder = make_recorder() if is_final else None
            result, verdicts = run_leg(plane, controlled, recorder)
            if args.controller == "ab":
                ab.setdefault(plane, {})[
                    "controlled" if controlled else "static"] = {
                    "ok": result.report.ok and slo.all_ok(verdicts),
                    "report": result.report.to_dict(),
                    "slo": slo.verdicts_to_dict(verdicts),
                    "breaches": [v.slo for v in verdicts if not v.ok],
                }
                if not args.json:
                    print(_ab_header(plane, plan.name, controlled))
                    print(result.report.format())
                    print(slo.format_verdicts(verdicts, plane))
                if not is_final:
                    continue
            if plane == "host":
                if result.load is not None:
                    overload["host"] = result.load.to_dict()
                lc = getattr(result, "lifecycle", None)
                if lc is not None:
                    lifecycle_info[plane] = lc
                series = getattr(result, "series", None)
                if series is not None:
                    ring_summaries[plane] = series.summaries()
                if getattr(result, "propagation", None) is not None:
                    propagation_info[plane] = result.propagation
                if getattr(result, "rotation", None) is not None:
                    rotation_info[plane] = result.rotation
                if getattr(result, "control", None) is not None:
                    control_info[plane] = result.control
            else:
                notes.extend(result.notes)
                if plan.has_load():
                    overload["device"] = {"offered": result.offered,
                                          "dropped": result.dropped}
                telemetry = getattr(result, "telemetry", None)
                if telemetry is not None:
                    ring_summaries[plane] = telemetry.summaries()
                prop = getattr(result, "propagation", None)
                if prop is not None:
                    # rows/coverage stay host-side arrays; the summary
                    # is the JSON-safe, printable digest
                    propagation_info[plane] = prop["summary"]
                if getattr(result, "control_final", None) is not None:
                    control_info[plane] = {
                        "final": result.control_final,
                        "decisions": result.control_decisions,
                    }
            slo_verdicts[plane] = verdicts
            reports.append(result.report)
            wd = getattr(result, "watchdog", None)
            if isinstance(wd, dict):
                watchdog_info[plane] = {k: v for k, v in wd.items()
                                        if k != "rows"}
            # a red run writes its repro artifacts (recording + digest
            # stream, and the black-box bundle beside it); green runs
            # keep neither — the recorder stayed in-memory
            if recorder is not None and not result.report.ok:
                path = os.path.join(
                    args.record_dir,
                    f"chaos-{plan.name}-{plane}.replay.jsonl")
                try:
                    recordings[plane] = recorder.save(path)
                except OSError as e:
                    # the repro artifact is best-effort: a bad
                    # --record-dir must not eat the invariant report of
                    # exactly the red run it was meant to make debuggable
                    print(f"record-on-fail: could not write {path}: {e}",
                          file=sys.stderr)
                try:
                    blackboxes[plane] = _dump_red_bundle(
                        args.record_dir, plan, plane, result)
                except (OSError, TypeError, ValueError) as e:
                    print(f"record-on-fail: could not dump black box: "
                          f"{e}", file=sys.stderr)

    timeline_path = None
    if args.export_timeline:
        # one bundle for the whole invocation: spans/flight ride the
        # process-global rings (added once), the host leg contributes
        # its lifecycle + SLO lanes, the device leg its round series +
        # control decisions mapped through the measured wall anchors
        from serf_tpu.obs.timeline import (
            DeviceRunAnchors,
            PiecewiseAnchors,
            export_run_timeline,
        )
        dev = final_results.get("device")
        try:
            anchors = None
            if dev is not None:
                if getattr(dev, "scan_walls", None):
                    # per-chunk stamps: a first-chunk compile skews
                    # only that chunk, not the whole run's round→wall
                    # mapping
                    anchors = PiecewiseAnchors(dev.scan_walls)
                elif "device" in device_anchor:
                    t0, t1 = device_anchor["device"]
                    anchors = DeviceRunAnchors(wall_start=t0, wall_end=t1,
                                               rounds=dev.rounds_run)
            timeline_path = export_run_timeline(
                args.export_timeline,
                host_result=final_results.get("host"),
                host_verdicts=final_verdicts.get("host"),
                device_result=dev, device_anchors=anchors,
                device_verdicts=final_verdicts.get("device"),
                meta={"plan": plan.name, "planes": list(planes),
                      "controller": args.controller})
        except Exception as e:  # noqa: BLE001 - same best-effort
            # contract as --record-on-fail: the artifact (bad path OR
            # exporter bug) must not eat the invariant report of the
            # run it was meant to make debuggable
            print(f"export-timeline: could not write "
                  f"{args.export_timeline}: {e!r}", file=sys.stderr)

    counters = degradation_counters()
    if args.json:
        out = {
            "plan": plan.name,
            "ok": all(r.ok for r in reports),
            "slo_ok": all(slo.all_ok(v) for v in slo_verdicts.values()),
            "reports": [r.to_dict() for r in reports],
            "slo": {p: slo.verdicts_to_dict(v)
                    for p, v in sorted(slo_verdicts.items())},
            "ring_summaries": ring_summaries,
            "degradation_counters": counters,
            "lowering_notes": notes,
            "overload": overload,
            "lifecycle": lifecycle_info,
            "propagation": propagation_info,
            "rotation": rotation_info,
            "device_mesh_devices": device_mesh,
            "recordings": recordings,
            "blackboxes": blackboxes,
            "watchdog": watchdog_info,
            "timeline": timeline_path,
        }
        if proc_info:
            out["proc"] = proc_info
        if args.controller != "off":
            out["controller"] = args.controller
            out["control"] = control_info
        if ab:
            out["control_ab"] = ab
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        if args.controller != "ab":
            # (ab mode printed each leg inline above)
            for r, plane in zip(reports, planes):
                print(r.format())
                if plane in slo_verdicts:
                    print(slo.format_verdicts(slo_verdicts[plane], plane))
        else:
            for plane in planes:
                st = ab[plane]["static"]
                ct = ab[plane]["controlled"]
                print(f"[{plane}] A/B: static "
                      f"{'GREEN' if st['ok'] else 'BREACHED (' + ', '.join(st['breaches'] + [i['name'] for i in st['report']['invariants'] if not i['ok']]) + ')'}"
                      f" -> controlled "
                      f"{'GREEN' if ct['ok'] else 'STILL RED'}")
        for plane, d in sorted(control_info.items()):
            decs = d.get("decisions", [])
            print(f"controller [{plane}]: {len(decs)} decision(s)"
                  + (f", final {d['final']}" if "final" in d
                     else f", values {d.get('values')}"))
        if proc_info:
            deg = ", ".join(f"{k}={v:.0f}" for k, v in
                            proc_info["survivor_degradation"].items()) \
                or "none"
            print(f"[proc] {proc_info['processes']} processes "
                  f"({proc_info['spawned_pids']} incarnations), settle "
                  f"convergence {proc_info['settle_convergence_s']:.2f}s, "
                  f"survivor degradation: {deg}")
        for plane, wd in sorted(watchdog_info.items()):
            first = wd.get("first_breach") or wd.get("first_violation")
            print(f"watchdog [{plane}]: "
                  f"{'ok' if wd.get('ok') else 'BREACHED'}"
                  + (f" (first: {first})" if first else ""))
        for plane, path in sorted(recordings.items()):
            print(f"repro recording [{plane}]: {path} "
                  "(replay with `python tools/replay.py replay <path>`)")
        for plane, path in sorted(blackboxes.items()):
            print(f"black-box bundle [{plane}]: {path} "
                  "(render with `python tools/blackbox.py render <path>`)")
        if timeline_path:
            print(f"timeline bundle: {timeline_path} "
                  "(open at https://ui.perfetto.dev)")
        if "device" in planes:
            print(f"device mesh: {device_mesh} device(s)"
                  + (" (sharded flagship round)" if device_mesh > 1
                     else ""))
        if notes:
            print("lowering notes: " + "; ".join(notes))
        if overload:
            print("overload accounting:")
            for plane, data in sorted(overload.items()):
                row = ", ".join(f"{k}={v}" for k, v in sorted(data.items()))
                print(f"  [{plane}] {row}")
        if lifecycle_info:
            # the per-stage latency decomposition of the host hot path
            # (obs/lifecycle.py), printed beside the invariant and SLO
            # verdicts it contextualizes
            from serf_tpu.obs.lifecycle import format_waterfall
            for plane, lc in sorted(lifecycle_info.items()):
                print(f"[{plane}] {format_waterfall(lc)}")
                if lc.get("slow"):
                    print(f"  slow-message flight events: {lc['slow']} "
                          f"(> {lc['slow_ms']:g} ms e2e)")
        if propagation_info:
            # the coverage-curve verdict (obs/propagation.py), printed
            # beside the invariant and SLO verdicts on both planes
            from serf_tpu.obs.propagation import format_propagation
            for plane, p in sorted(propagation_info.items()):
                print(format_propagation(p, plane))
        for plane, rot in sorted(rotation_info.items()):
            probes = rot.get("probes", {})
            print(f"rotation [{plane}]: "
                  f"{'converged' if rot.get('converged') else 'NOT CONVERGED'}"
                  f" in {rot.get('latency_s', float('nan')):.3f}s "
                  f"({rot.get('reconcile_rounds', 0)} reconcile round(s)), "
                  f"{len(rot.get('ops', []))} op(s), mid-rotation probes "
                  f"{probes.get('delivered', 0)}/{probes.get('offered', 0)}"
                  f" delivered, decrypt fallback/fail "
                  f"{rot.get('decrypt_fallback', 0):.0f}/"
                  f"{rot.get('decrypt_fail', 0):.0f}, rings -> "
                  f"{rot.get('expected_primary', '?')}")
        print("degradation counters:")
        for name in sorted(counters):
            print(f"  {name} = {counters[name]:.0f}")
    if args.controller == "ab":
        # A/B verdict: the CONTROLLED legs must be all-green (invariants
        # AND SLOs) — the static legs are allowed (expected, for the
        # control-* plans) to breach
        return 0 if all(ab[p]["controlled"]["ok"] for p in ab) else 1
    # rotation-latency is part of the rotation proof, not advisory: an
    # encrypted run that reconverges too slowly (or never) exits red even
    # when every invariant held (other SLOs stay report-only here)
    rotation_ok = all(v.ok for vs in slo_verdicts.values()
                      for v in vs if v.slo == "rotation-latency")
    return 0 if (all(r.ok for r in reports) and rotation_ok) else 1


def _ab_header(plane: str, plan_name: str, controlled: bool) -> str:
    leg = "CONTROLLED" if controlled else "STATIC"
    return f"=== [{plane}] {plan_name}: {leg} leg " + "=" * 20


if __name__ == "__main__":
    sys.exit(main())
