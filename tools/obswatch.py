#!/usr/bin/env python
"""obswatch: run the continuous-telemetry scenario and judge the SLOs.

The SLO plane's CLI + its tier-1 self-check.  One run:

- **device leg** — a seeded partition+loss FaultPlan through the
  flagship ``cluster_round`` with per-round telemetry collected inside
  the scan (one ``device_get`` for the whole run), timed so the
  measured rounds/sec can be judged against the analytic bandwidth
  ceiling (``models/accounting``);
- **host leg** — the loopback self-check chaos plan with the
  ``MetricsSampler`` ticking throughout, so counter/gauge/flight rings
  carry the run;
- both planes judged against THE shared ``obs.slo.SLO_TABLE`` —
  verdicts, burn rates, anomaly flags, ring tails.

    python tools/obswatch.py                   # report, human-readable
    python tools/obswatch.py --json            # machine-readable
    python tools/obswatch.py --self-check      # tier-1 hook: exit 0
                                               # iff every verdict green
    python tools/obswatch.py --self-check --degraded
        # deliberately raise loss PAST heal (no settle budget, 90%
        # loss to the end): convergence cannot complete, the run MUST
        # fire `slo-breach` and exit nonzero — the test that pins the
        # breach path actually works

Exit 0 iff every evaluated (non-skipped) SLO verdict on every plane is
green.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the demo scenario must run on CPU even where a TPU plugin is registered
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def device_plan(degraded: bool = False):
    """The device-leg scenario: warm → bisect+loss → heal.  ``degraded``
    keeps 90% loss running past the heal with NO settle budget — the
    cluster cannot re-converge, by construction (the breach fixture)."""
    from serf_tpu.faults.plan import FaultPhase, FaultPlan

    phases = [
        FaultPhase(name="warm", rounds=10),
        FaultPhase(name="bisect+loss", rounds=10,
                   partitions=((0, 1), (2, 3)), drop=0.05),
    ]
    if degraded:
        # 10 rounds like every other phase: the whole scenario (green
        # or degraded) then reuses ONE compiled 10-round phase scan
        phases.append(FaultPhase(name="loss-past-heal", rounds=10,
                                 drop=0.9))
    return FaultPlan(
        name="obswatch-degraded" if degraded else "obswatch",
        n=4, seed=5, phases=tuple(phases),
        settle_s=8.0, settle_rounds=0 if degraded else 40)


def run_device_leg(n: int, degraded: bool):
    """Run the device scenario with telemetry + the sentinel propagation
    tracer + wall timing; returns (verdict list, ring store, rps,
    ceiling, propagation summary dict)."""
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.models.accounting import round_traffic
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig
    from serf_tpu.obs import slo

    cfg = ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=32, peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8)
    plan = device_plan(degraded)
    t0 = time.perf_counter()
    result = run_device_plan(plan, cfg, collect_telemetry=True,
                             collect_propagation=True,
                             collect_invariants=True)
    elapsed = time.perf_counter() - t0
    # wall rps INCLUDING compile — an understatement, which is the safe
    # direction for the measurement-integrity SLO (measured <= ceiling)
    rps = result.rounds_run / max(elapsed, 1e-9)
    ceiling = round_traffic(cfg).ceiling_rounds_per_sec()
    verdicts = slo.judge_device_run(result, plan, rps=rps,
                                    ceiling=ceiling)
    prop = result.propagation["summary"] if result.propagation else None
    wd = {k: v for k, v in (result.watchdog or {}).items()
          if k != "rows"}
    return verdicts, result.telemetry, rps, ceiling, prop, wd


def run_host_leg():
    """Run the host self-check chaos plan (sampler rings + the message
    lifecycle ledger ride along); returns (verdict list — including the
    stage-latency rows judged from the ledger snapshot —, ring store,
    lifecycle snapshot)."""
    from serf_tpu.faults.host import run_host_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.obs import slo

    plan = named_plan("self-check")
    with tempfile.TemporaryDirectory(prefix="serf-obswatch-") as td:
        result = asyncio.run(run_host_plan(plan, tmp_dir=td))
    return (slo.judge_host_run(result, plan), result.series,
            result.lifecycle, result.propagation, result.watchdog)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=64,
                    help="device-leg simulated node count (default 64)")
    ap.add_argument("--json", action="store_true",
                    help="emit verdicts + ring tails as JSON")
    ap.add_argument("--self-check", action="store_true",
                    help="tier-1 hook (same run; named for symmetry "
                         "with the chaos/obstop hooks)")
    ap.add_argument("--degraded", action="store_true",
                    help="raise loss past heal so the SLOs MUST breach "
                         "(device leg only; exit becomes nonzero)")
    ap.add_argument("--device-only", action="store_true",
                    help="skip the host leg (fast in-process smoke)")
    ap.add_argument("--tail", type=int, default=16,
                    help="ring-tail points per series in --json output")
    args = ap.parse_args(argv)

    from serf_tpu.obs import flight, slo

    verdicts = {}
    rings = {}
    propagation = {}
    watchdog = {}
    dev_verdicts, dev_store, rps, ceiling, dev_prop, dev_wd = \
        run_device_leg(args.n, args.degraded)
    verdicts["device"] = dev_verdicts
    if dev_store is not None:
        rings["device"] = dev_store
    if dev_prop is not None:
        propagation["device"] = dev_prop
    if dev_wd:
        watchdog["device"] = dev_wd
    lifecycle_snap = None
    if not args.device_only and not args.degraded:
        host_verdicts, host_store, lifecycle_snap, host_prop, host_wd = \
            run_host_leg()
        verdicts["host"] = host_verdicts
        if host_store is not None:
            rings["host"] = host_store
        if host_prop is not None:
            propagation["host"] = host_prop
        if host_wd:
            watchdog["host"] = host_wd

    ok = all(slo.all_ok(v) for v in verdicts.values())
    breaches = flight.flight_dump(kind="slo-breach")
    if args.json:
        print(json.dumps({
            "ok": ok,
            "device_rps": round(rps, 2),
            "device_ceiling_rps": round(ceiling, 1),
            "verdicts": {p: slo.verdicts_to_dict(v)
                         for p, v in sorted(verdicts.items())},
            "slo_breach_events": breaches,
            "rings": {p: s.tail(last=args.tail)
                      for p, s in sorted(rings.items())},
            "lifecycle": lifecycle_snap,
            "propagation": propagation,
            "watchdog": watchdog,
        }, indent=1, sort_keys=True))
    else:
        from serf_tpu.obs.propagation import format_propagation
        from serf_tpu.obs.watchdog import format_invariants
        for plane in sorted(verdicts):
            print(slo.format_verdicts(verdicts[plane], plane))
            if plane in propagation:
                print(format_propagation(propagation[plane], plane))
        if "device" in watchdog:
            print(format_invariants(watchdog["device"], "device"))
        if "host" in watchdog:
            wd = watchdog["host"]
            print(f"[host] watchdog: "
                  f"{'GREEN' if wd.get('ok') else 'BREACHED'} "
                  f"({wd.get('ticks', 0)} tick(s), "
                  f"{len(wd.get('armed') or ())} armed, "
                  f"{len(wd.get('bundles') or ())} bundle(s))")
        if lifecycle_snap is not None:
            from serf_tpu.obs.lifecycle import format_waterfall
            print(format_waterfall(lifecycle_snap))
        print(f"device: {rps:.1f} measured rounds/s vs analytic "
              f"ceiling {ceiling:.1f}")
        if breaches:
            print(f"slo-breach flight events: {len(breaches)}")
            for e in breaches[-4:]:
                print(f"  [{e.get('plane')}] {e.get('slo')}: "
                      f"{e.get('detail')}")
    if not ok:
        print("obswatch: FAIL — SLO breach (see verdicts above)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
