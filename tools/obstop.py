#!/usr/bin/env python
"""obstop: render a ClusterSnapshot — fleet health at a glance.

Spins an in-process cluster (LoopbackNetwork), fires one
``_serf_stats`` scatter/fold from the first node, and renders the
resulting ``ClusterSnapshot`` as a table (or ``--json`` for machines).
This doubles as the cluster-plane contract self-check wired into tier-1
(tests/test_cluster_obs.py): if the aggregation path regresses —
payloads stop fitting the response budget, a node stops answering, the
fold drops fields — this exits non-zero.

    python tools/obstop.py                # 3-node demo, table output
    python tools/obstop.py --nodes 5      # bigger demo cluster
    python tools/obstop.py --json         # machine-readable snapshot

Embedding against a live cluster is one call on any node:
``snap = await serf.cluster_stats()``; ``obs.render_table(snap)``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the demo cluster must run on CPU even where a TPU plugin is registered
os.environ.setdefault("JAX_PLATFORMS", "cpu")


async def _demo_snapshot(n: int, timeout: float):
    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.host.query import QueryParam
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    nodes = []
    try:
        for i in range(n):
            nodes.append(await Serf.create(
                net.bind(f"n{i}"), Options.local(), f"node-{i}"))
        for s in nodes[1:]:
            await s.join("n0")

        deadline = asyncio.get_running_loop().time() + 10.0
        while asyncio.get_running_loop().time() < deadline:
            if all(len(s.members()) == n for s in nodes):
                break
            await asyncio.sleep(0.02)

        return await nodes[0].cluster_stats(QueryParam(timeout=timeout))
    finally:
        for s in nodes:
            await s.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=3,
                    help="demo cluster size (default 3)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="stats query timeout in seconds (default 2.0)")
    ap.add_argument("--json", action="store_true",
                    help="emit the snapshot as JSON instead of a table")
    args = ap.parse_args(argv)

    from serf_tpu.obs.cluster import render_table

    snap = asyncio.run(_demo_snapshot(args.nodes, args.timeout))
    if args.json:
        print(json.dumps(snap.to_dict(), indent=2, sort_keys=True))
    else:
        print(render_table(snap))

    # self-check: the contract the tier-1 hook pins
    if snap.responders < args.nodes:
        print(f"obstop: FAIL — only {snap.responders}/{args.nodes} nodes "
              "answered _serf_stats", file=sys.stderr)
        return 1
    for nid, d in snap.nodes.items():
        if not isinstance(d.get("health"), (int, float)) or not d.get("hc"):
            print(f"obstop: FAIL — node {nid} report missing health fields",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
