#!/usr/bin/env python
"""obstop: render a ClusterSnapshot — fleet health at a glance.

Spins an in-process cluster (LoopbackNetwork), fires one
``_serf_stats`` scatter/fold from the first node, and renders the
resulting ``ClusterSnapshot`` as a table (or ``--json`` for machines).
This doubles as the cluster-plane contract self-check wired into tier-1
(tests/test_cluster_obs.py): if the aggregation path regresses —
payloads stop fitting the response budget, a node stops answering, the
fold drops fields — this exits non-zero.

    python tools/obstop.py                # 3-node demo, table output
    python tools/obstop.py --nodes 5      # bigger demo cluster
    python tools/obstop.py --json         # machine-readable snapshot
    python tools/obstop.py --watch 0.5    # periodic refresh off the
                                          # sampler RINGS (sparklines)
    python tools/obstop.py --watch 0.5 --json   # ring-tail JSON

``--watch <interval>`` switches from the one-shot ``cluster_stats()``
scatter to the continuous-telemetry plane: a ``MetricsSampler`` ticks
at the interval and each refresh renders the ring series — last value
plus a sparkline of the last-W deltas per aggregate — so rates and
trends are visible, not just levels — followed by the message-lifecycle
stage waterfall (``obs/lifecycle.py``: per-stage latency bars,
transport/decode/dispatch/apply/queue-wait/tee, over sampled
messages) and the live watchdog verdict (``obs/watchdog.py``: last
tick, armed invariants, black-box bundles written).  ``--iterations``
bounds the demo (default 3; a live embedding would loop forever).

Embedding against a live cluster is one call on any node:
``snap = await serf.cluster_stats()``; ``obs.render_table(snap)``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the demo cluster must run on CPU even where a TPU plugin is registered
os.environ.setdefault("JAX_PLATFORMS", "cpu")


async def _demo_cluster(n: int):
    """Stand up the joined demo cluster; returns (net, nodes).  On any
    startup failure the already-created nodes are shut down cleanly
    before the exception propagates — callers only own cleanup once
    this returns."""
    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    nodes = []
    try:
        for i in range(n):
            nodes.append(await Serf.create(
                net.bind(f"n{i}"), Options.local(), f"node-{i}"))
        for s in nodes[1:]:
            await s.join("n0")
        deadline = asyncio.get_running_loop().time() + 10.0
        while asyncio.get_running_loop().time() < deadline:
            if all(len(s.members()) == n for s in nodes):
                break
            await asyncio.sleep(0.02)
    except BaseException:
        for s in nodes:
            await s.shutdown()
        raise
    return net, nodes


async def _demo_snapshot(n: int, timeout: float):
    from serf_tpu.host.query import QueryParam

    _net, nodes = await _demo_cluster(n)
    try:
        return await nodes[0].cluster_stats(QueryParam(timeout=timeout))
    finally:
        for s in nodes:
            await s.shutdown()


#: --watch renders these ring series when present (rates from counter
#: deltas, levels from gauges); everything else folds into the
#: "busiest other series" rows
WATCH_KEY_SERIES = ("serf.events", "serf.messages.sent",
                    "serf.member.join", "serf.health.score",
                    "serf.loop.lag-ms")
WATCH_W = 16


def _render_rings(store, iteration: int) -> str:
    from serf_tpu.obs.timeseries import sparkline

    lines = [f"obstop --watch refresh #{iteration} "
             f"({len(store.names())} ring series)"]
    rows = []
    names = store.names()
    busiest = sorted(
        (n for n in names if n not in WATCH_KEY_SERIES),
        key=lambda n: -abs(store.get(n).window(WATCH_W)
                           * (1 if store.get(n).kind == 'delta' else 0)))
    for name in [n for n in WATCH_KEY_SERIES if n in names] + busiest[:6]:
        s = store.get(name)
        last = s.last()
        rows.append((name, s.kind,
                     f"{last:g}" if last is not None else "-",
                     sparkline(s.values(), width=WATCH_W)))
    if rows:
        w0 = max(len(r[0]) for r in rows)
        for name, kind, last, spark in rows:
            lines.append(f"  {name.ljust(w0)}  {kind:<5} {last:>10}  "
                         f"{spark}")
    return "\n".join(lines)


async def _watch(n: int, interval: float, iterations: int,
                 as_json: bool, tail: int) -> int:
    """Periodic refresh off the sampler rings (not a cluster_stats
    scatter per tick): the cluster runs, the sampler snapshots the sink
    + flight recorder each interval, and every refresh renders last-W
    deltas per series plus the message-lifecycle stage waterfall
    (obs/lifecycle.py: per-stage latency bars over sampled messages —
    the demo fires one user event per refresh so the ledger has
    traffic to decompose)."""
    from serf_tpu.obs import lifecycle
    from serf_tpu.obs.timeseries import MetricsSampler

    if as_json and iterations <= 0:
        # JSON mode emits ONE ring-tail dump after the loop; an
        # unbounded loop would silently never produce a byte
        print("obstop: --watch --json needs a bounded --iterations "
              "(the ring tail is dumped once, after the last refresh)",
              file=sys.stderr)
        return 2

    # sample every message: a three-node demo has little traffic, and
    # the waterfall should render from the first refresh
    led = lifecycle.LifecycleLedger(sample_n=1)
    prev_led = lifecycle.set_global_ledger(led)
    _net, nodes = await _demo_cluster(n)
    sampler = MetricsSampler(interval_s=interval)
    # the always-on watchdog rides the same tick: each refresh also
    # prints its live verdict (last tick, armed invariants, bundles)
    from serf_tpu.obs.watchdog import Watchdog, arm_serf_invariants
    wd = Watchdog(store=sampler.store)
    arm_serf_invariants(wd, lambda: dict(enumerate(nodes)))
    try:
        i = 0
        while iterations <= 0 or i < iterations:
            try:
                await nodes[0].user_event(f"obstop-watch-{i}", b"",
                                          coalesce=False)
            except Exception:  # noqa: BLE001 - demo traffic, best-effort
                pass
            await asyncio.sleep(interval)
            sampler.sample()
            wd.tick()
            i += 1
            if not as_json:
                print(_render_rings(sampler.store, i))
                print(lifecycle.format_waterfall(led.snapshot()))
                print(wd.format())
        if as_json:
            print(json.dumps({
                "ticks": sampler.ticks,
                "series": sampler.store.names(),
                "tail": sampler.store.tail(last=tail),
                "lifecycle": led.snapshot(),
                "watchdog": wd.state(),
            }, indent=1, sort_keys=True))
        return 0 if (sampler.ticks > 0 and len(sampler.store) > 0
                     and wd.ticks > 0) else 1
    finally:
        # teardown first, restore after: shutdown traffic must land on
        # the demo's scoped ledger, not leak onto the restored one
        # (same ordering rule as run_host_plan)
        for s in nodes:
            await s.shutdown()
        lifecycle.set_global_ledger(prev_led)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=3,
                    help="demo cluster size (default 3)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="stats query timeout in seconds (default 2.0)")
    ap.add_argument("--json", action="store_true",
                    help="emit the snapshot as JSON instead of a table")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                    help="periodic refresh off the sampler rings at "
                         "this interval (sparkline last-%d deltas per "
                         "series) instead of a one-shot cluster_stats"
                         % WATCH_W)
    ap.add_argument("--iterations", type=int, default=3,
                    help="refreshes in --watch mode (<=0 = forever; "
                         "default 3)")
    ap.add_argument("--tail", type=int, default=16,
                    help="--watch --json: ring-tail points per series")
    args = ap.parse_args(argv)

    if args.watch > 0:
        return asyncio.run(_watch(args.nodes, args.watch,
                                  args.iterations, args.json, args.tail))

    from serf_tpu.obs.cluster import render_table

    snap = asyncio.run(_demo_snapshot(args.nodes, args.timeout))
    if args.json:
        print(json.dumps(snap.to_dict(), indent=2, sort_keys=True))
    else:
        print(render_table(snap))

    # self-check: the contract the tier-1 hook pins
    if snap.responders < args.nodes:
        print(f"obstop: FAIL — only {snap.responders}/{args.nodes} nodes "
              "answered _serf_stats", file=sys.stderr)
        return 1
    for nid, d in snap.nodes.items():
        if not isinstance(d.get("health"), (int, float)) or not d.get("hc"):
            print(f"obstop: FAIL — node {nid} report missing health fields",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
