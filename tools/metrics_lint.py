#!/usr/bin/env python
"""Lint: every emitted metric name is documented, and vice versa.

The README "Observability" table is the contract operators build dashboards
against; this tool keeps it honest in both directions:

- every metric name the tree emits (``metrics.incr/gauge/observe`` call
  sites, plus the name->value dict literals inside the device plane's
  ``emit_*_metrics`` functions, where the gauge call loops over the dict)
  must have a row in the table;
- every row in the table must correspond to at least one emission site
  (no stale docs).

Dynamic name segments are normalized on both sides — an f-string
``serf.queue.{self.name}`` at a call site and ``serf.queue.<name>`` in the
table both become ``serf.queue.<>`` — so parameterized families stay
documented as one row.

Exit 0 = in sync; exit 1 prints the drift.  Wired into tier-1 as a fast
test (tests/test_observability.py); also runnable directly:

    python tools/metrics_lint.py
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Dict, Iterable, Set

REPO = Path(__file__).resolve().parent.parent
README = REPO / "README.md"
#: where metric emissions live; tests are deliberately excluded (they
#: emit throwaway names when exercising the sink itself)
SCAN = ["serf_tpu", "bench.py"]
#: a string is a candidate metric name only under this grammar
NAME_RE = re.compile(r"^(serf|memberlist)\.[a-z0-9_.<>{}-]+$")
#: README table rows: | `name` | type | ...
ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")
_DYNAMIC = re.compile(r"(\{[^{}]*\}|<[^<>]*>)")


def normalize(name: str) -> str:
    """Collapse every dynamic segment ({expr} or <doc>) to ``<>``."""
    return _DYNAMIC.sub("<>", name)


def _joined_str_pattern(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("{}")
    return "".join(parts)


def emitted_names(paths: Iterable[Path]) -> Dict[str, Set[str]]:
    """{normalized_name: {file:line, ...}} across all scanned sources."""
    out: Dict[str, Set[str]] = {}

    def add(raw: str, path: Path, lineno: int) -> None:
        if not NAME_RE.match(normalize(raw).replace("<>", "x")):
            return
        out.setdefault(normalize(raw), set()).add(
            f"{path.relative_to(REPO)}:{lineno}")

    for path in paths:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            # metrics.incr/gauge/observe("name"...) and f-string variants
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("incr", "gauge", "observe")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "metrics"
                    and node.args):
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    add(arg.value, path, node.lineno)
                elif isinstance(arg, ast.JoinedStr):
                    add(_joined_str_pattern(arg), path, node.lineno)
            # device-plane emitters: {"name": value, ...} dict literals
            # inside emit_*_metrics functions (emitted via a loop)
            elif (isinstance(node, ast.FunctionDef)
                  and node.name.startswith("emit_")
                  and node.name.endswith("_metrics")):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict):
                        for key in sub.keys:
                            if (isinstance(key, ast.Constant)
                                    and isinstance(key.value, str)):
                                add(key.value, path, sub.lineno)
    return out


def documented_names(readme: Path) -> Dict[str, str]:
    """{normalized_name: raw_name} from the README Observability table."""
    out: Dict[str, str] = {}
    in_section = False
    for line in readme.read_text().splitlines():
        if line.startswith("## "):
            in_section = line.strip() == "## Observability"
            continue
        if not in_section:
            continue
        m = ROW_RE.match(line)
        if m and m.group(1) != "Metric":
            out[normalize(m.group(1))] = m.group(1)
    return out


def run() -> int:
    files = []
    for entry in SCAN:
        p = REPO / entry
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    emitted = emitted_names(files)
    documented = documented_names(README)
    if not documented:
        print("metrics_lint: no table rows found under '## Observability' "
              f"in {README}")
        return 1

    rc = 0
    for name in sorted(set(emitted) - set(documented)):
        print(f"metrics_lint: EMITTED BUT UNDOCUMENTED: {name} "
              f"(at {', '.join(sorted(emitted[name]))}) — add a row to "
              "README.md '## Observability'")
        rc = 1
    for name in sorted(set(documented) - set(emitted)):
        print(f"metrics_lint: DOCUMENTED BUT NEVER EMITTED: "
              f"{documented[name]} — delete the README row or restore the "
              "emission")
        rc = 1
    if rc == 0:
        print(f"metrics_lint: OK — {len(emitted)} metric names, "
              "README table in sync")
    return rc


if __name__ == "__main__":
    sys.exit(run())
