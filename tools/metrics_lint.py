#!/usr/bin/env python
"""Lint: every emitted metric name is documented, and vice versa.

Since ISSUE 8 this is a THIN WRAPPER over the serflint registry pass
(``serf_tpu.analysis.registry``) — the PR-1 one-off grew into the
repo-wide static-analysis plane, and the metric extraction, README-table
parsing, and drift checks all live there now (shared with the
``reg-metric-*``/``reg-doc-drift`` rules).  The original contract is
unchanged and still tier-1:

- every metric name the tree emits must have a row in the README
  "## Observability" table;
- every row in the table must correspond to at least one emission site;
- (new) both must be declared in the ONE registry
  (``serf_tpu/analysis/registry.py`` METRICS).

Exit 0 = in sync; exit 1 prints the drift.  Runnable directly:

    python tools/metrics_lint.py

The module-level API (``SCAN``/``README``/``normalize``/
``emitted_names``/``documented_names``/``run``) is kept verbatim for the
tier-1 hooks in tests/test_cluster_obs.py and tests/test_observability.py.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from serf_tpu.analysis import registry as _registry        # noqa: E402

README = REPO / "README.md"
#: where metric emissions live; tests are deliberately excluded (they
#: emit throwaway names when exercising the sink itself)
SCAN = ["serf_tpu", "bench.py"]

normalize = _registry.normalize
NAME_RE = _registry.NAME_RE
ROW_RE = _registry.ROW_RE


def emitted_names(paths):
    """{normalized_name: {file:line, ...}} across all scanned sources."""
    return _registry.emitted_metric_names(paths)


def documented_names(readme: Path):
    """{normalized_name: raw_name} from the README Observability table."""
    return _registry.documented_metric_names(readme)


def run() -> int:
    files = []
    for entry in SCAN:
        p = REPO / entry
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    emitted = emitted_names(files)
    drift = _registry.metric_drift_report(files, README, _registry.METRICS,
                                          emitted=emitted)
    for line in drift:
        print(f"metrics_lint: {line}")
    if not drift:
        print(f"metrics_lint: OK — {len(emitted)} metric "
              "names, registry + README table in sync")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(run())
