#!/usr/bin/env python
"""Per-phase in-scan cost ablation for the flagship round at 1M nodes.

The tunnel adds ~5-8 ms of dispatch latency per jitted CALL, so
microbenching single ops wildly overstates in-scan costs.  This tool times
each protocol phase the way the flagship runs it — inside a
``lax.scan`` over many rounds, one dispatch, host-transfer-synced — and
prints a cost table: rounds/s and ms/round for cumulative phase stacks
plus isolated suspects (top_k selection, the age-plane rewrite).

Run on the real chip in the foreground; falls back to CPU honestly.
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_fact,
    make_state,
    pick_bounded,
    round_step,
)
from serf_tpu.models.failure import (
    FailureConfig,
    declare_round,
    probe_round,
    refute_round,
)
from serf_tpu.models.swim import ClusterConfig, make_cluster, run_cluster

N = int(os.environ.get("SERF_TPU_ABLATE_N", 1_000_000))
ROUNDS = int(os.environ.get("SERF_TPU_ABLATE_ROUNDS", 50))

gcfg = GossipConfig(n=N, k_facts=64, peer_sampling="rotation")
fcfg = FailureConfig(suspicion_rounds=12, max_new_facts=8,
                     probe_schedule="round_robin")
cfg = ClusterConfig(gossip=gcfg, failure=fcfg, push_pull_every=16,
                    with_failure=True, with_vivaldi=True)


def seeded():
    key = jax.random.key(0)
    st = make_cluster(cfg, key)
    g = st.gossip
    for i in range(8):
        g = inject_fact(g, gcfg, subject=(i * (N // 8)) % N,
                        kind=K_USER_EVENT, incarnation=0, ltime=i + 1,
                        origin=(i * (N // 8)) % N)
    return st._replace(gossip=g)


def scan_timer(tag, body, state_fn, rounds=ROUNDS, reps=3):
    """Time scan(body, rounds) with a host-transfer completion barrier.

    ``state_fn`` builds a fresh state per call — the jit donates its input,
    so a shared state object would be deleted after the first timer."""
    state = state_fn()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(st, key):
        keys = jax.random.split(key, rounds)
        final, _ = jax.lax.scan(lambda c, k: (body(c, k), ()), st, keys)
        return final

    import numpy as np
    key = jax.random.key(1)
    key, k = jax.random.split(key)
    state = run(state, k)                      # compile + warm
    leaf = jax.tree.leaves(state)[0]
    np.asarray(jax.device_get(leaf)).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(reps):
        key, k = jax.random.split(key)
        state = run(state, k)
    leaf = jax.tree.leaves(state)[0]
    np.asarray(jax.device_get(leaf)).ravel()[:1]
    dt = time.perf_counter() - t0
    ms = 1000 * dt / (reps * rounds)
    print(f"{tag:34s} {ms:8.3f} ms/round   {reps * rounds / dt:10.1f} rounds/s",
          flush=True)
    return ms


def _anchor(s, *vals):
    """Fold values into the carried round counter so XLA cannot dead-code-
    eliminate the work that produced them (a constant-zero multiply would be
    constant-folded; a data-dependent parity bit cannot be)."""
    acc = s.round
    for v in vals:
        acc = acc + (jnp.sum(v.astype(jnp.int32)) & 1)
    return s._replace(round=acc)


def isolated_only(g0):
    """The isolated-suspect timers (shared by full and --skip-stacks runs)."""
    # the ORIGINAL flat top_k selection, inlined — the production
    # pick_bounded now takes the grouped path at this N, so calling it
    # would A/B the new path against itself
    def pick_flat(s, k):
        cand = s.alive & (jax.random.uniform(k, (N,)) < 0.001)
        score = cand.astype(jnp.float32) * (
            1.0 + jax.random.uniform(k, (N,)))
        vals, idx = jax.lax.top_k(score, 8)
        return _anchor(s, vals > 0.0, idx)
    scan_timer("pick flat-top_k@1M x1", pick_flat, g0)

    # the production path (two-level strided groups at this N)
    def pick_prod(s, k):
        cand = s.alive & (jax.random.uniform(k, (N,)) < 0.001)
        chosen, subjects, active = pick_bounded(cand, 8, k)
        return _anchor(s, chosen, subjects, active)
    scan_timer("pick_bounded (production) x1", pick_prod, g0)

    # a full stamp-plane select+rewrite alone (what the old stored-age
    # tick cost every round; the nibble-packed plane now pays this only
    # on the merge's learn write — this isolates that traffic)
    def plane_body(s, k):
        bumped = jnp.where(s.stamp < 255, s.stamp + 1, s.stamp)
        return s._replace(stamp=bumped, round=s.round + 1)
    scan_timer("stamp plane rewrite", plane_body, g0)

    # rolled_rows of the packet plane alone (summed so all three rolls
    # materialize; a masked-to-zero merge would be folded away entirely)
    def roll_body(s, k):
        from serf_tpu.models.dissemination import rolled_rows, sample_offsets
        offs = sample_offsets(k, 3, N)
        x = s.known
        acc = jnp.zeros_like(x)
        for f in range(3):
            acc = acc | rolled_rows(x, offs[f])
        return _anchor(s._replace(round=s.round + 1), acc)
    scan_timer("3x rolled_rows(known) only", roll_body, g0)


def main():
    print(f"platform: {jax.devices()[0].device_kind}  N={N} rounds={ROUNDS}",
          flush=True)

    g0 = lambda: seeded().gossip

    if os.environ.get("SERF_TPU_ABLATE_SKIP_STACKS"):
        isolated_only(g0)
        print("ablation complete", flush=True)
        return

    # cumulative stacks over the gossip state
    scan_timer("gossip only (round_step)",
               lambda s, k: round_step(s, gcfg, k), g0)
    scan_timer("gossip+probe",
               lambda s, k: probe_round(
                   round_step(s, gcfg, jax.random.fold_in(k, 0)),
                   gcfg, fcfg, jax.random.fold_in(k, 1)), g0)
    scan_timer("gossip+probe+refute",
               lambda s, k: refute_round(
                   probe_round(
                       round_step(s, gcfg, jax.random.fold_in(k, 0)),
                       gcfg, fcfg, jax.random.fold_in(k, 1)),
                   gcfg, fcfg, jax.random.fold_in(k, 2)), g0)
    scan_timer("swim (g+p+r+declare)",
               lambda s, k: declare_round(
                   refute_round(
                       probe_round(
                           round_step(s, gcfg, jax.random.fold_in(k, 0)),
                           gcfg, fcfg, jax.random.fold_in(k, 1)),
                       gcfg, fcfg, jax.random.fold_in(k, 2)),
                   gcfg, fcfg, jax.random.fold_in(k, 3)), g0)

    # full flagship
    def flag_body(s, k):
        from serf_tpu.models.swim import cluster_round
        return cluster_round(s, cfg, k)
    scan_timer("flagship cluster_round", flag_body, seeded)

    isolated_only(g0)
    print("ablation complete", flush=True)


if __name__ == "__main__":
    main()
