"""Print the HBM traffic model for the bench configurations.

Usage:
    python tools/hbm_report.py [--n 1000000] [--hlo [N]]

``--hlo N`` additionally compiles the sustained flagship at N nodes
(default 65536; forced CPU unless SERF_TPU_HBM_TPU=1) and prints XLA's
own bytes-accessed figure next to the model.  See
serf_tpu/models/accounting.py; budgets pinned in tests/test_accounting.py.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--hlo", type=int, nargs="?", const=65_536,
                    default=None)
    args = ap.parse_args()

    import jax

    if os.environ.get("SERF_TPU_HBM_TPU") != "1":
        # env rule: ad-hoc scripts must not touch the tunnel
        jax.config.update("jax_platforms", "cpu")

    from serf_tpu.models.accounting import (
        hlo_bytes_per_round,
        round_traffic,
    )
    from serf_tpu.models.swim import flagship_config

    cfg = flagship_config(args.n)
    for regime in ("sustained", "detection", "active", "quiescent"):
        r = round_traffic(cfg, regime=regime)
        print(r.table())
        print()

    # kernel dispatch paths (ISSUE 7): XLA vs standalone vs fused pallas
    from serf_tpu.models.accounting import kernel_path_summary
    s = kernel_path_summary(cfg)
    print("kernel-path comparison (sustained):")
    for path, v in s["paths"].items():
        passes = v["passes_by_plane"].get("stamp", 0.0)
        print(f"  {path:<8} {v['total_bytes'] / 1e6:>8.1f} MB/round   "
              f"stamp-plane passes {passes:.3f}   "
              f"ceiling {v['ceiling_rps']:,.0f} rps")
    fk = s["fused_vs_kernels"]
    print(f"  fused vs kernels: {fk['bytes_saved'] / 1e6:.1f} MB/round "
          f"saved ({fk['reduction_factor']}x), "
          f"{fk['stamp_passes_removed']} full stamp-plane pass(es)/round "
          f"removed\n")

    if args.hlo:
        import functools

        from serf_tpu.models.swim import make_cluster, run_cluster_sustained

        cfg_s = flagship_config(args.hlo)
        state = make_cluster(cfg_s, jax.random.key(0))
        run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg_s,
                                        events_per_round=2),
                      static_argnames=("num_rounds",))
        hlo = hlo_bytes_per_round(run, state, key=jax.random.key(1),
                                  num_rounds=10)
        model = round_traffic(cfg_s, regime="sustained").total_bytes
        if hlo is None:
            print(f"HLO cross-check @n={args.hlo}: backend exposes no "
                  f"cost analysis")
        else:
            print(f"HLO cross-check @n={args.hlo}: compiled "
                  f"{hlo / 1e6:.1f} MB/round vs model "
                  f"{model / 1e6:.1f} MB/round "
                  f"(ratio {hlo / model:.2f})")


if __name__ == "__main__":
    main()
