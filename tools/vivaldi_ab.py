"""Device-plane Vivaldi latency-filter accuracy A/B at scale.

VERDICT r4 next-8: the device plane's per-NODE median filter (an O(N)
stand-in for the reference's O(N²)-state per-PEER filter,
coordinate.rs:708-723) defaults OFF.  This tool quantifies the deviation
at 100k nodes under two noise regimes:

- ``clean``:  rtt_true × lognormal jitter (σ=0.1) — ordinary variance
- ``spiky``:  the same plus 5% ×10 spikes (retries/queueing bursts) —
  the failure mode latency filters exist for

and runs the HOST per-peer oracle (the faithful reference
implementation) at small N on the same noise model as the reference
point.  Writes VIVALDI_AB.json; the default-on/off decision and numbers
live in STATUS.md.

Usage: python tools/vivaldi_ab.py [--n 100000] [--rounds 300]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def device_run(n, rounds, fsize, spike_p, seed=0):
    import functools

    import jax
    import jax.numpy as jnp

    from serf_tpu.models.dissemination import rolled_rows, sample_offsets
    from serf_tpu.models.vivaldi import (
        VivaldiConfig,
        ground_truth_rtt_rolled,
        make_vivaldi,
        mean_relative_error,
        vivaldi_update,
    )

    cfg = VivaldiConfig(latency_filter_size=fsize)
    key = jax.random.key(seed)
    k_pos, key = jax.random.split(key)
    positions = jax.random.uniform(k_pos, (n, 3), jnp.float32) * 0.05
    dev = make_vivaldi(n, cfg)

    def round_fn(dev, k):
        k_off, k_jit, k_spk, k_upd = jax.random.split(k, 4)
        off = sample_offsets(k_off, 1, n)[0]
        rtt = ground_truth_rtt_rolled(positions, off)
        # multiplicative lognormal jitter + occasional large spikes
        jitter = jnp.exp(jax.random.normal(k_jit, (n,)) * 0.1)
        spike = jnp.where(
            jax.random.bernoulli(k_spk, spike_p, (n,)), 10.0, 1.0)
        rtt_obs = rtt * jitter * spike
        return vivaldi_update(dev, cfg, None, rtt_obs, k_upd,
                              peer_roll=off), ()

    run = jax.jit(functools.partial(jax.lax.scan, round_fn))
    dev, _ = run(dev, jax.random.split(key, rounds))
    err = float(mean_relative_error(dev, cfg, positions,
                                    jax.random.key(99)))
    return err


def host_oracle_run(n, rounds, spike_p, seed=0):
    """The reference per-peer filter implementation (host plane), same
    noise model, random-pair observations."""
    import random as pyrandom

    import numpy as np

    from serf_tpu.host.coordinate import CoordinateClient

    rng = pyrandom.Random(seed)
    nprng = np.random.default_rng(seed)
    positions = nprng.uniform(0, 0.05, size=(n, 3)).astype(np.float64)
    clients = [CoordinateClient() for _ in range(n)]

    def true_rtt(i, j):
        return 0.005 + float(np.linalg.norm(positions[i] - positions[j]))

    for _ in range(rounds):
        # one observation per node per round, like the device rotation
        off = rng.randrange(1, n)
        for i in range(n):
            j = (i + off) % n
            rtt = true_rtt(i, j) * float(np.exp(nprng.normal() * 0.1))
            if nprng.random() < spike_p:
                rtt *= 10.0
            clients[i].update(f"node-{j}", clients[j].get_coordinate(),
                              rtt)
    errs = []
    for _ in range(4096):
        i, j = rng.randrange(n), rng.randrange(n)
        if i == j:
            continue
        est = clients[i].get_coordinate().distance_to(
            clients[j].get_coordinate())
        t = true_rtt(i, j)
        errs.append(abs(est - t) / t)
    return float(np.mean(errs))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--rounds", type=int, default=300)
    ap.add_argument("--host-n", type=int, default=192)
    ap.add_argument("--host-rounds", type=int, default=120)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")   # env rule: never the tunnel

    out = {"n": args.n, "rounds": args.rounds, "device": {}, "host": {}}
    for regime, spike_p in (("clean", 0.0), ("spiky", 0.05)):
        for fsize in (1, 3):
            err = device_run(args.n, args.rounds, fsize, spike_p)
            out["device"][f"{regime}_filter{fsize}"] = round(err, 4)
            print(f"device n={args.n} {regime:>5} filter={fsize}: "
                  f"mean rel err {err:.4f}", flush=True)
        herr = host_oracle_run(args.host_n, args.host_rounds, spike_p)
        out["host"][f"{regime}_perpeer_n{args.host_n}"] = round(herr, 4)
        print(f"host  n={args.host_n} {regime:>5} per-peer filter: "
              f"mean rel err {herr:.4f}", flush=True)

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "VIVALDI_AB.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
