#!/usr/bin/env python
"""Record, replay and diff deterministic chaos-run recordings.

    python tools/replay.py record --plan partition-heal-loss \\
        --plane device --out run.jsonl
    python tools/replay.py replay run.jsonl --out replayed.jsonl
    python tools/replay.py diff run.jsonl replayed.jsonl --json

``record`` runs a named FaultPlan on one plane with the recorder
attached and writes the recording (ingress steps + per-round
membership-view digests).  ``replay`` re-executes a recording on its
plane and diffs the replayed digest stream against the source —
exit 0 iff bit-identical.  ``diff`` compares two recordings' digest
streams and reports the FIRST DIVERGENT ROUND plus the per-node view
delta at that round; exit is nonzero on any divergence, so a replay
pipeline can gate on it.  See README "Record & replay" for the format
spec and the determinism contract.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def cmd_record(args) -> int:
    from serf_tpu.faults.plan import named_plan, plan_names
    from serf_tpu.replay.recording import RunRecorder
    from serf_tpu.replay.selfcheck import default_replay_cfg

    try:
        plan = named_plan(args.plan)
    except KeyError:
        print(f"unknown plan {args.plan!r}; available: "
              f"{', '.join(plan_names())}", file=sys.stderr)
        return 2
    recorder = RunRecorder()
    if args.plane == "device":
        from serf_tpu.faults.device import run_device_plan

        result = run_device_plan(
            plan, default_replay_cfg(args.n, args.k_facts),
            recorder=recorder)
    else:
        from serf_tpu.faults.host import run_host_plan

        with tempfile.TemporaryDirectory(prefix="serf-replay-") as td:
            result = asyncio.run(
                run_host_plan(plan, tmp_dir=td, recorder=recorder))
    rec = recorder.to_recording()
    path = rec.save(args.out)
    views = len(rec.views())
    if args.json:
        print(json.dumps({"path": path, "plane": args.plane,
                          "plan": plan.name, "views": views,
                          "invariants_ok": bool(result.report.ok)},
                         indent=1, sort_keys=True))
    else:
        print(result.report.format())
        print(f"recorded {views} view digest(s) -> {path}")
    return 0 if result.report.ok else 1


def cmd_replay(args) -> int:
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.recording import Recording
    from serf_tpu.replay.replayer import replay_recording

    rec = Recording.load(args.recording)
    with tempfile.TemporaryDirectory(prefix="serf-replay-") as td:
        replayed = replay_recording(
            rec, tmp_dir=td if rec.plane == "host" else None
        ).to_recording()
    if args.out:
        replayed.save(args.out)
    rep = diff_recordings(rec, replayed)
    if args.json:
        out = rep.to_dict()
        out["replayed_to"] = args.out
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        print(rep.format())
        if args.out:
            print(f"replay digest stream -> {args.out}")
    return 0 if rep.ok else 1


def cmd_diff(args) -> int:
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.recording import Recording

    rep = diff_recordings(Recording.load(args.a), Recording.load(args.b))
    if args.json:
        print(json.dumps(rep.to_dict(), indent=1, sort_keys=True))
    else:
        print(rep.format())
    return 0 if rep.ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="run a plan and write a recording")
    rec.add_argument("--plan", default="partition-heal-loss")
    rec.add_argument("--plane", choices=("host", "device"),
                     default="device")
    rec.add_argument("--n", type=int, default=96,
                     help="device-plane simulated node count")
    rec.add_argument("--k-facts", type=int, default=32)
    rec.add_argument("--out", default="serf-replay.jsonl")
    rec.add_argument("--json", action="store_true")
    rec.set_defaults(fn=cmd_record)

    rp = sub.add_parser("replay", help="re-execute a recording and "
                                       "diff against it")
    rp.add_argument("recording")
    rp.add_argument("--out", default=None,
                    help="also write the replayed digest stream here")
    rp.add_argument("--json", action="store_true")
    rp.set_defaults(fn=cmd_replay)

    df = sub.add_parser("diff", help="compare two recordings' digest "
                                     "streams")
    df.add_argument("a")
    df.add_argument("b")
    df.add_argument("--json", action="store_true")
    df.set_defaults(fn=cmd_diff)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
