#!/usr/bin/env python
"""Per-phase profiler for the flagship ``cluster_round`` (CLI).

Jits every round phase in isolation on a warmed sustained-load state,
times each behind a device→host barrier, pulls XLA ``cost_analysis()``
bytes/flops, cross-checks the analytic byte model, and flags the phase
whose wall share its bytes cannot explain — the localization tool for
any measured-vs-roofline gap (serf_tpu/obs/profile.py has the method).

Usage:

    python tools/roundprof.py [--n 65536] [--k 64] [--calls 3] [--json]

``--json`` prints the machine contract on stdout (one JSON object:
``n/k/backend/phases[]/whole_round/attributed_bytes_frac/
anomalous_phase``; each phase row carries ``wall_ms``, ``xla_bytes``,
``model_bytes``, ``achieved_gbps``, ``roofline_frac``, ``wall_share``,
``byte_share``, ``excess``); the human table always goes to stderr.
Runs on whatever backend JAX resolves — on the CPU fallback the
roofline fractions are still computed against the v5e HBM constant and
labeled via ``backend``.  Tier-1 runs this as a self-check
(tests/test_roundprof.py): the contract keys and the ≥90% byte
attribution are pinned there.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=65536)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--events", type=int, default=2,
                    help="user events injected per round (sustained load)")
    ap.add_argument("--calls", type=int, default=3,
                    help="timed steady calls per phase")
    ap.add_argument("--warm", type=int, default=24,
                    help="sustained warmup rounds before profiling")
    ap.add_argument("--mesh", type=int, default=0,
                    help="profile the SHARDED flagship path on this "
                         "many devices (0 = unsharded; the count must "
                         "divide --n and be <= the visible devices)")
    ap.add_argument("--schedule", choices=("ring", "allgather"),
                    default="ring",
                    help="ICI schedule of the sharded exchange leg")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON contract on stdout")
    args = ap.parse_args(argv)

    from serf_tpu.models.swim import flagship_config
    from serf_tpu.obs.profile import profile_round, profile_table

    mesh = None
    if args.mesh:
        import jax

        from serf_tpu.parallel.mesh import make_mesh
        if args.mesh > len(jax.devices()):
            sys.stderr.write(
                f"--mesh {args.mesh} exceeds the {len(jax.devices())} "
                "visible device(s)\n")
            return 2
        if args.n % args.mesh != 0:
            # the sharded profile's per-chip byte columns assume exactly
            # N/P per chip; an indivisible N would silently profile the
            # GSPMD fallback while claiming the authored schedule
            sys.stderr.write(
                f"--mesh {args.mesh} does not divide --n {args.n}\n")
            return 2
        mesh = make_mesh(args.mesh)

    cfg = flagship_config(args.n, k_facts=args.k)
    prof = profile_round(cfg, events_per_round=args.events,
                         timed_calls=args.calls, warm_rounds=args.warm,
                         mesh=mesh, schedule=args.schedule)
    sys.stderr.write(profile_table(prof) + "\n")
    if args.json:
        print(json.dumps(prof))
    return 0


if __name__ == "__main__":
    sys.exit(main())
