#!/usr/bin/env python
"""Per-phase profiler for the flagship ``cluster_round`` (CLI).

Jits every round phase in isolation on a warmed sustained-load state,
times each behind a device→host barrier, pulls XLA ``cost_analysis()``
bytes/flops, cross-checks the analytic byte model, and flags the phase
whose wall share its bytes cannot explain — the localization tool for
any measured-vs-roofline gap (serf_tpu/obs/profile.py has the method).

Usage:

    python tools/roundprof.py [--n 65536] [--k 64] [--calls 3] [--json]

``--json`` prints the machine contract on stdout (one JSON object:
``n/k/backend/phases[]/whole_round/attributed_bytes_frac/
anomalous_phase``; each phase row carries ``wall_ms``, ``xla_bytes``,
``model_bytes``, ``achieved_gbps``, ``roofline_frac``, ``wall_share``,
``byte_share``, ``excess``); the human table always goes to stderr.
Runs on whatever backend JAX resolves — on the CPU fallback the
roofline fractions are still computed against the v5e HBM constant and
labeled via ``backend``.  Tier-1 runs this as a self-check
(tests/test_roundprof.py): the contract keys and the ≥90% byte
attribution are pinned there.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=65536)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--events", type=int, default=2,
                    help="user events injected per round (sustained load)")
    ap.add_argument("--calls", type=int, default=3,
                    help="timed steady calls per phase")
    ap.add_argument("--warm", type=int, default=24,
                    help="sustained warmup rounds before profiling")
    ap.add_argument("--mesh", type=int, default=0,
                    help="profile the SHARDED flagship path on this "
                         "many devices (0 = unsharded; the count must "
                         "divide --n and be <= the visible devices)")
    ap.add_argument("--schedule", choices=("ring", "allgather"),
                    default="ring",
                    help="ICI schedule of the sharded exchange leg")
    ap.add_argument("--fused", action="store_true",
                    help="profile the FUSED pallas round vs the phased "
                         "(standalone-kernel) round side by side and "
                         "print the removed-pass delta (single-device: "
                         "the phased kernels cannot shard, so --mesh is "
                         "rejected); --json emits "
                         "{'fused': ..., 'phased': ..., 'delta': ...}")
    ap.add_argument("--stamp-unit", type=int, default=0,
                    help="profile quarter-deferred stamp flushes at this "
                         "unit (2 or 4) against the per-round flavor, "
                         "same config/seeds, and print the removed "
                         "stamp-pass delta (0 = off); --json emits "
                         "{'deferred': ..., 'per_round': ..., 'delta': "
                         "...}")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON contract on stdout")
    args = ap.parse_args(argv)

    from serf_tpu.models.swim import flagship_config
    from serf_tpu.obs.profile import profile_round, profile_table

    if args.stamp_unit:
        if args.fused or args.mesh:
            sys.stderr.write("--stamp-unit is a single-device XLA-path "
                             "A/B; drop --fused/--mesh\n")
            return 2
        return _stamp_ab(args)
    if args.fused:
        if args.mesh:
            # the phased (standalone-kernel) side of the A/B is
            # single-device only; silently profiling unsharded under a
            # --mesh flag would mislabel the answer
            sys.stderr.write("--fused is a single-device kernel A/B "
                             "(the standalone kernels cannot shard); "
                             "drop --mesh or profile the sharded fused "
                             "path without --fused\n")
            return 2
        return _fused_ab(args)

    mesh = None
    if args.mesh:
        import jax

        from serf_tpu.parallel.mesh import make_mesh
        if args.mesh > len(jax.devices()):
            sys.stderr.write(
                f"--mesh {args.mesh} exceeds the {len(jax.devices())} "
                "visible device(s)\n")
            return 2
        if args.n % args.mesh != 0:
            # the sharded profile's per-chip byte columns assume exactly
            # N/P per chip; an indivisible N would silently profile the
            # GSPMD fallback while claiming the authored schedule
            sys.stderr.write(
                f"--mesh {args.mesh} does not divide --n {args.n}\n")
            return 2
        mesh = make_mesh(args.mesh)

    cfg = flagship_config(args.n, k_facts=args.k)
    prof = profile_round(cfg, events_per_round=args.events,
                         timed_calls=args.calls, warm_rounds=args.warm,
                         mesh=mesh, schedule=args.schedule)
    sys.stderr.write(profile_table(prof) + "\n")
    if args.json:
        print(json.dumps(prof))
    return 0


def _fused_ab(args) -> int:
    """``--fused``: the fused family vs the phased standalone kernels,
    same config/seeds, with the removed-pass delta — the observational
    side of ``accounting.kernel_path_summary`` (the fused merge
    maintains the sendable cache in-kernel, so selection's full
    stamp-plane read disappears from the round)."""
    import dataclasses

    from serf_tpu.models.swim import flagship_config
    from serf_tpu.obs.profile import profile_round, profile_table

    base = flagship_config(args.n, k_facts=args.k)
    profs = {}
    for name, fused in (("phased", False), ("fused", True)):
        cfg = dataclasses.replace(
            base, gossip=dataclasses.replace(base.gossip, use_pallas=True,
                                             fused_kernels=fused))
        profs[name] = profile_round(cfg, events_per_round=args.events,
                                    timed_calls=args.calls,
                                    warm_rounds=args.warm)
        want = "fused" if fused else "kernels"
        if profs[name]["kernel_path"] != want:
            # a shape/VMEM rejection fell back to XLA: refuse to print an
            # XLA-vs-XLA comparison labeled as the kernel A/B
            sys.stderr.write(
                "--fused: the %s flavor dispatched kernel_path=%r, not "
                "%r (pallas rejected n=%d k=%d — see the pallas-fallback "
                "flight event); pick a supported shape\n" % (
                    name, profs[name]["kernel_path"], want, args.n,
                    args.k))
            return 2
        sys.stderr.write(profile_table(profs[name]) + "\n\n")
    fp = profs["fused"]["full_plane_passes"]
    pp = profs["phased"]["full_plane_passes"]
    planes = sorted(set(fp) | set(pp))
    delta = {
        "stamp_passes_removed": round(pp.get("stamp", 0.0)
                                      - fp.get("stamp", 0.0), 3),
        "passes": {p: {"phased": pp.get(p, 0.0), "fused": fp.get(p, 0.0)}
                   for p in planes},
        "wall_ms": {name: round(sum(r["wall_ms"]
                                    for r in profs[name]["phases"]), 3)
                    for name in profs},
        "attributed_bytes_frac": {
            name: profs[name]["attributed_bytes_frac"] for name in profs},
    }
    sys.stderr.write(
        "fused vs phased kernel round @n=%d: stamp-plane passes "
        "%.2f -> %.2f (%.2f full-plane pass(es)/round removed — the "
        "selection's stamp read; the cache is maintained in-kernel); "
        "phase wall %s -> %s ms\n" % (
            args.n, pp.get("stamp", 0.0), fp.get("stamp", 0.0),
            delta["stamp_passes_removed"],
            delta["wall_ms"]["phased"], delta["wall_ms"]["fused"]))
    if args.json:
        print(json.dumps({"fused": profs["fused"],
                          "phased": profs["phased"], "delta": delta}))
    return 0


def _stamp_ab(args) -> int:
    """``--stamp-unit U``: quarter-deferred stamp flushes vs the
    per-round flavor, same config/seeds — the observational side of
    ``accounting.round_traffic(stamp_deferred=)`` (the per-learn-round
    stamp R+W becomes a once-per-cohort flush plus the overlay ride;
    ISSUE 18)."""
    import dataclasses

    from serf_tpu.models.swim import flagship_config
    from serf_tpu.obs.profile import profile_round, profile_table

    base = flagship_config(args.n, k_facts=args.k)
    profs = {}
    for name, unit in (("per_round", 1), ("deferred", args.stamp_unit)):
        cfg = dataclasses.replace(
            base, gossip=dataclasses.replace(base.gossip,
                                             stamp_flush_unit=unit))
        profs[name] = profile_round(cfg, events_per_round=args.events,
                                    timed_calls=args.calls,
                                    warm_rounds=args.warm)
        sys.stderr.write(profile_table(profs[name]) + "\n\n")
    dp = profs["deferred"]["full_plane_passes"]
    pp = profs["per_round"]["full_plane_passes"]
    planes = sorted(set(dp) | set(pp))
    delta = {
        "stamp_passes_removed": round(pp.get("stamp", 0.0)
                                      - dp.get("stamp", 0.0), 3),
        "overlay_passes_added": round(dp.get("overlay", 0.0), 3),
        "passes": {p: {"per_round": pp.get(p, 0.0),
                       "deferred": dp.get(p, 0.0)} for p in planes},
        "model_bytes": {
            name: profs[name]["whole_round"]["model_amortized_bytes"]
            for name in profs},
        "wall_ms": {name: round(sum(r["wall_ms"]
                                    for r in profs[name]["phases"]), 3)
                    for name in profs},
        "attributed_bytes_frac": {
            name: profs[name]["attributed_bytes_frac"] for name in profs},
    }
    sys.stderr.write(
        "deferred (unit %d) vs per-round stamps @n=%d: stamp-plane "
        "passes %.2f -> %.2f (%.2f full-plane pass(es)/round removed — "
        "the per-learn-round stamp R+W now flushes once per cohort; "
        "+%.2f overlay pass(es)); modeled %.1f -> %.1f MB/round; "
        "phase wall %s -> %s ms\n" % (
            args.stamp_unit, args.n, pp.get("stamp", 0.0),
            dp.get("stamp", 0.0), delta["stamp_passes_removed"],
            delta["overlay_passes_added"],
            delta["model_bytes"]["per_round"] / 1e6,
            delta["model_bytes"]["deferred"] / 1e6,
            delta["wall_ms"]["per_round"], delta["wall_ms"]["deferred"]))
    if args.json:
        print(json.dumps({"deferred": profs["deferred"],
                          "per_round": profs["per_round"],
                          "delta": delta}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
