"""Ring-vs-allgather crossover sweep on a virtual 8-device mesh.

A THIN config loop over the flagship sharded round (ISSUE 6: there is
exactly ONE sharded round in the tree — ``parallel.ring.
sharded_round_step``, the same code ``cluster_round`` runs with a mesh);
the A/B is just ``schedule="allgather"`` vs ``schedule="ring"`` on the
same jitted step.  CPU-mesh timings quantify the collective SCHEDULE
(dispatch count, materialization, overlap shape) — not ICI bandwidth, so
``ring_wins: false`` here is expected and NOT dispositive; the decision
of record is ``accounting.ici_round_traffic``'s α-β arithmetic
(``schedule.recommended``), which this sweep embeds per row.

Writes MULTICHIP_AB.json at the repo root and prints the table.

Usage: python tools/multichip_ab.py [--devices 8] [--reps 20]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--per-device", type=int, nargs="*",
                    default=[1024, 16384, 131072])
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={args.devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import functools

    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from serf_tpu.models.accounting import ici_round_traffic
    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_USER_EVENT,
        inject_fact,
        make_state,
    )
    from serf_tpu.models.swim import flagship_config
    from serf_tpu.parallel.mesh import make_mesh, shard_state, state_shardings
    from serf_tpu.parallel.ring import sharded_round_step

    d = args.devices
    mesh = make_mesh(d)
    results = []
    for n_local in args.per_device:
        n = n_local * d
        # iid sampling: the mode where the exchange is a data-dependent
        # gather — the all-gather schedule materializes the packet plane;
        # the ring schedule resolves it in D-1 ppermute hops
        cfg = GossipConfig(n=n, k_facts=64, peer_sampling="iid")
        g = make_state(cfg)
        for i in range(8):
            g = inject_fact(g, cfg, subject=i, kind=K_USER_EVENT,
                            incarnation=0, ltime=i + 1,
                            origin=(i * (n // 8)) % n)
        g = shard_state(g, mesh)
        sh = state_shardings(g, mesh)

        # the thin config loop: same flagship step, two schedules
        steps = {
            sched: jax.jit(functools.partial(sharded_round_step, cfg=cfg,
                                             mesh=mesh, schedule=sched),
                           out_shardings=sh)
            for sched in ("allgather", "ring")
        }

        def rps(stepfn, g0):
            g1 = stepfn(g0, key=jax.random.key(1))     # compile + warm
            int(np.asarray(g1.round))
            t0 = time.perf_counter()
            gg = g0
            for i in range(args.reps):
                gg = stepfn(gg, key=jax.random.key(2 + i))
            int(np.asarray(gg.round))                  # completion barrier
            return args.reps / (time.perf_counter() - t0)

        ag_rps, ring_rps = rps(steps["allgather"], g), rps(steps["ring"], g)
        model = ici_round_traffic(flagship_config(n), d)
        row = {
            "n": n, "n_per_device": n_local,
            "allgather_rps": round(ag_rps, 1),
            "ring_rps": round(ring_rps, 1),
            "ring_wins": ring_rps > ag_rps,
            "model_allgather_bytes_per_chip":
                model["iid_allgather_bytes_per_chip"],
            "model_ring_bytes_per_chip":
                model["iid_ring_bytes_per_chip"],
            # the decision of record (ICI α-β arithmetic, not CPU wall)
            "model_schedule_recommended": model["schedule"]["recommended"],
        }
        results.append(row)
        print(f"n={n:>8} ({n_local}/dev): allgather {ag_rps:8.1f} rps, "
              f"ring {ring_rps:8.1f} rps -> "
              f"{'RING' if row['ring_wins'] else 'ALLGATHER'} wins on "
              f"CPU wall; model recommends "
              f"{row['model_schedule_recommended'].upper()}",
              flush=True)

    crossover = next((r["n"] for r in results if r["ring_wins"]), None)
    out = {
        "devices": d, "reps": args.reps, "results": results,
        "crossover_n": crossover,
        "note": "CPU virtual mesh: collective schedule shape, not ICI "
                "bandwidth — ring_wins on CPU is NOT dispositive; the "
                "decision of record is accounting.ici_round_traffic's "
                "schedule.recommended (per-phase per-chip bytes + α-β "
                "launch model); see STATUS.md",
        "ici_model_1m_8chip": ici_round_traffic(flagship_config(1_000_000),
                                                8),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTICHIP_AB.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}; CPU-wall crossover at n={crossover}")


if __name__ == "__main__":
    main()
